#!/usr/bin/env python
"""tokengen — generate token network artifacts (reference `cmd/tokengen`).

Subcommands:
  gen fabtoken  --output DIR [--issuers N] [--owners N] [--auditor]
  gen dlog      --output DIR --base B --exponent E [...]

Writes public parameters + wallet key material as JSON files, mirroring
the reference's artifact generation for network bootstrap.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.crypto.serialization import dumps
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenPublicParams
from fabric_token_sdk_tpu.drivers import identity


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)
    print(f"wrote {path}")


def _gen_identities(outdir: str, args, rng) -> tuple:
    issuers, auditor = [], b""
    for i in range(args.issuers):
        key = sign.keygen(rng)
        ident = identity.pk_identity(key.public)
        issuers.append(ident)
        _write(
            os.path.join(outdir, f"issuers/issuer{i}.json"),
            dumps({"sk": key.sk, "identity": ident}),
        )
    if args.auditor:
        key = sign.keygen(rng)
        auditor = identity.pk_identity(key.public)
        _write(
            os.path.join(outdir, "auditor/auditor.json"),
            dumps({"sk": key.sk, "identity": auditor}),
        )
    for i in range(args.owners):
        key = sign.keygen(rng)
        _write(
            os.path.join(outdir, f"owners/owner{i}.json"),
            dumps({"sk": key.sk, "identity": identity.pk_identity(key.public)}),
        )
    return issuers, auditor


def cmd_fabtoken(args) -> None:
    rng = random.Random(args.seed) if args.seed is not None else None
    pp = FabTokenPublicParams()
    issuers, auditor = _gen_identities(args.output, args, rng)
    for ident in issuers:
        pp.add_issuer(ident)
    if auditor:
        pp.add_auditor(auditor)
    _write(os.path.join(args.output, "fabtoken_pp.json"), pp.serialize())


def cmd_dlog(args) -> None:
    rng = random.Random(args.seed) if args.seed is not None else None
    pp = setup(base=args.base, exponent=args.exponent, rng=rng)
    issuers, auditor = _gen_identities(args.output, args, rng)
    for ident in issuers:
        pp.add_issuer(ident)
    if auditor:
        pp.add_auditor(auditor)
    pp.validate()
    _write(os.path.join(args.output, "zkatdlog_pp.json"), pp.serialize())


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="tokengen")
    sub = ap.add_subparsers(dest="cmd", required=True)
    gen = sub.add_parser("gen")
    gsub = gen.add_subparsers(dest="driver", required=True)
    for name in ("fabtoken", "dlog"):
        p = gsub.add_parser(name)
        p.add_argument("--output", required=True)
        p.add_argument("--issuers", type=int, default=1)
        p.add_argument("--owners", type=int, default=2)
        p.add_argument("--auditor", action="store_true")
        p.add_argument("--seed", type=int, default=None)
        if name == "dlog":
            p.add_argument("--base", type=int, default=16)
            p.add_argument("--exponent", type=int, default=2)
    args = ap.parse_args(argv)
    if args.driver == "fabtoken":
        cmd_fabtoken(args)
    else:
        cmd_dlog(args)


if __name__ == "__main__":
    main()
