"""Precompile the verify + prove data planes into the persistent XLA cache.

Usage:
    python cmd/ftswarmup.py                 # full set (stages + pairing)
    python cmd/ftswarmup.py --no-pairing    # group-math stage tiles only
    python cmd/ftswarmup.py --no-prover     # skip prover-only programs
    python cmd/ftswarmup.py --list          # show the program inventory
                                            # (tagged verify/prove planes)

Prints ONE JSON summary line, e.g.:
    {"metric": "warmup", "programs": 12, "seconds": 412.3,
     "backend_compiles": 12, "cache_hits": 0, "cache_misses": 12, ...}

NOTE on cache keys: XLA compile options are part of the persistent-cache
key, and the test suite forces `--xla_force_host_platform_device_count=8`
(tests/conftest.py) — so warm the TEST environment with
`FTS_WARMUP=1 pytest tests/` (the session fixture shares the suite's
flags), and use this CLI for the bench/production environment.

Run this once after changing kernels, jax versions, or clearing
`~/.cache/fts_tpu_jax` (override: FTS_TPU_JAX_CACHE): afterwards every
`BatchedTransferVerifier.verify`, test session, and bench run replays the
whole verify plane from persistent-cache hits — zero recompiles
(`cache_misses` stays 0 in the `ftsmetrics show` compile summary).
A metrics sidecar (default WARMUP.metrics.json, override
FTS_METRICS_SIDECAR) records per-program compile seconds; inspect with
`python cmd/ftsmetrics.py show WARMUP.metrics.json`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ftswarmup", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "--no-pairing",
        action="store_true",
        help="skip the (large) miller/product/final-exp pairing tiles",
    )
    ap.add_argument(
        "--no-prover",
        action="store_true",
        help="skip programs used only by the batched prover "
        "(the shared verify+prove tiles still compile)",
    )
    ap.add_argument(
        "--list",
        action="store_true",
        help="list the canonical program inventory without compiling",
    )
    ap.add_argument(
        "--quiet",
        action="store_true",
        help="suppress per-program progress lines on stderr",
    )
    args = ap.parse_args(argv)

    from fabric_token_sdk_tpu.ops import warmup as wu
    from fabric_token_sdk_tpu.utils import metrics as mx

    if args.list:
        for name, _fn, shapes in wu.all_programs(
            not args.no_pairing, not args.no_prover
        ):
            planes = f"[{wu.program_planes(name)}]"
            print(
                f"{name:<24} {planes:<16} "
                f"{' x '.join(str(s) for s in shapes)}"
            )
        return 0

    mx.enable(True)
    mx.install_sidecar(
        os.environ.get("FTS_METRICS_SIDECAR", "WARMUP.metrics.json")
    )
    mx.REGISTRY.set_meta("entry", "ftswarmup.py")

    def progress(name, dt):
        if not args.quiet:
            print(f"[fts-warmup] {name} compiled in {dt:.1f}s",
                  file=sys.stderr, flush=True)

    summary = wu.warmup(
        include_pairing=not args.no_pairing,
        include_prover=not args.no_prover,
        progress=progress,
    )
    summary.pop("per_program", None)
    print(json.dumps({"metric": "warmup", **summary}), flush=True)
    mx.flush_sidecar()
    return 0


if __name__ == "__main__":
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )
    sys.exit(main())
