"""Pretty-print / diff `*.metrics.json` sidecar dumps.

Usage:
    python cmd/ftsmetrics.py show BENCH.metrics.json
    python cmd/ftsmetrics.py show --prometheus BENCH.metrics.json
    python cmd/ftsmetrics.py diff BENCH_r05.metrics.json BENCH_r06.metrics.json

The sidecar format is whatever `utils/metrics.py` `Registry.snapshot()`
emits: meta, phase timeline, counters, gauges, histograms, span summary.
See docs/OBSERVABILITY.md for the metric-name taxonomy.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def _fmt_s(v: float) -> str:
    if v >= 60:
        return f"{v / 60:.1f}m"
    if v >= 1:
        return f"{v:.1f}s"
    return f"{v * 1000:.1f}ms"


def _print_kv(title: str, rows, fmt=str) -> None:
    if not rows:
        return
    print(f"\n{title}")
    width = max(len(k) for k, _ in rows)
    for k, v in rows:
        print(f"  {k:<{width}}  {fmt(v)}")


def show(path: str, prometheus: bool = False) -> None:
    d = _load(path)
    if prometheus:
        # re-serialize counters/gauges through a scratch registry so one
        # exporter owns that part of the text format
        from fabric_token_sdk_tpu.utils.metrics import Registry, _prom_name, _prom_num

        reg = Registry()
        for name, v in d.get("counters", {}).items():
            reg.counter(name).inc(v)
        for name, v in d.get("gauges", {}).items():
            reg.gauge(name).set(v)
        sys.stdout.write(reg.to_prometheus())
        # histograms come from the snapshot dict directly (the sidecar
        # stores per-bucket counts for the non-empty buckets only)
        lines = []
        for name, h in sorted(d.get("histograms", {}).items()):
            m = _prom_name(name)
            lines.append(f"# TYPE {m} histogram")
            cum = 0
            finite = {
                float(le): c
                for le, c in h.get("buckets", {}).items()
                if le != "+Inf"
            }
            for le in sorted(finite):
                cum += finite[le]
                lines.append(f'{m}_bucket{{le="{_prom_num(le)}"}} {cum}')
            cum += h.get("buckets", {}).get("+Inf", 0)
            lines.append(f'{m}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{m}_sum {_prom_num(h.get('sum', 0))}")
            lines.append(f"{m}_count {h.get('count', 0)}")
            for q in ("p50", "p95", "p99"):
                if q in h:
                    lines.append(f"{m}_{q} {_prom_num(h[q])}")
        if lines:
            sys.stdout.write("\n".join(lines) + "\n")
        return

    print(f"== {path}")
    meta = d.get("meta", {})
    if meta:
        _print_kv("meta", sorted(meta.items()))

    phases = d.get("phases", [])
    if phases:
        print("\nphases")
        for p in phases:
            el = p.get("elapsed_s")
            el_s = _fmt_s(el) if el is not None else "(unfinished)"
            attrs = p.get("attrs", {})
            extra = "".join(f" {k}={v}" for k, v in attrs.items())
            print(f"  {p['name']:<18} {el_s:>10}{extra}")
        total = sum(p.get("elapsed_s", 0.0) for p in phases)
        print(f"  {'TOTAL':<18} {_fmt_s(total):>10}")

    _print_kv("counters", sorted(d.get("counters", {}).items()))

    # one-line compile/cache health: the cold-cache-regression check.
    # programs = distinct XLA programs backend-compiled this run; a warm
    # persistent cache shows programs=0 with cache_hits > 0.
    comp = d.get("histograms", {}).get(
        "jax.core.compile.backend_compile_duration.seconds", {}
    )
    ctr = d.get("counters", {})
    print(
        f"\ncompile summary: programs={comp.get('count', 0)}"
        f" compile_sum={_fmt_s(comp.get('sum', 0.0))}"
        f" cache_hits={ctr.get('jax.compilation_cache.cache_hits', 0)}"
        f" cache_misses={ctr.get('jax.compilation_cache.cache_misses', 0)}"
        f" load_failures={ctr.get('jax.cache.load_failures', 0)}"
    )

    # one-line block-pipeline health: how much of the validate plane rode
    # the batched device path vs the host fallback
    blocks = ctr.get("ledger.blocks.committed", 0)
    if blocks:
        bsize = d.get("histograms", {}).get("ledger.block.size", {})
        txs = int(bsize.get("sum", 0))
        batched = ctr.get("ledger.validate.batched", 0)
        host = ctr.get("ledger.validate.host", 0)
        frac = batched / (batched + host) if (batched + host) else 0.0
        print(
            f"block summary: blocks={blocks} txs={txs}"
            f" txs_per_block={txs / blocks:.1f}"
            f" batched={batched} host={host} batched_frac={frac:.2f}"
        )

    # one-line prove-plane health: how much proof GENERATION rode the
    # batched device prover vs the host prover (and device-error
    # fallbacks — nonzero fallbacks mean the degrade-only contract fired)
    p_batches = ctr.get("batch.prove.batches", 0)
    p_txs = ctr.get("batch.prove.txs", 0)
    p_host = ctr.get("batch.prove.host", 0)
    p_fall = ctr.get("batch.prove.host_fallbacks", 0)
    if p_batches or p_host or p_fall:
        denom = p_txs + p_host
        frac = p_txs / denom if denom else 0.0
        print(
            f"prove summary: batches={p_batches} device_txs={p_txs}"
            f" host={p_host} host_fallbacks={p_fall}"
            f" device_frac={frac:.2f}"
        )

    # one-line sign-plane health: how much signature verification rode
    # the batched device plane vs the host loop (fallbacks nonzero means
    # the degrade-only contract fired), plus the identity parse-cache
    # hit rate shared by both paths
    s_batches = ctr.get("batch.sign.batches", 0)
    s_rows = ctr.get("batch.sign.rows", 0)
    s_host = ctr.get("batch.sign.host", 0)
    s_fall = ctr.get("batch.sign.host_fallbacks", 0)
    ic_hits = ctr.get("identity.cache.hits", 0)
    ic_miss = ctr.get("identity.cache.misses", 0)
    if s_batches or s_host or s_fall or ic_hits or ic_miss:
        lookups = ic_hits + ic_miss
        hit_rate = ic_hits / lookups if lookups else 0.0
        print(
            f"sign summary: batches={s_batches} device_rows={s_rows}"
            f" host={s_host} host_fallbacks={s_fall}"
            f" identity_cache_hit_rate={hit_rate:.2f}"
        )

    # one-line resilience health: circuit-breaker transitions, open-
    # breaker rejections, bounded-dispatch timeouts and abandoned-worker
    # straggler completions — nonzero opens/timeouts mean a device plane
    # was degraded and the commit path rode its host fallback
    r_open = ctr.get("resilience.breaker.open", 0)
    r_close = ctr.get("resilience.breaker.close", 0)
    r_probe = ctr.get("resilience.breaker.probe", 0)
    r_rej = ctr.get("resilience.breaker.rejected", 0)
    b_calls = ctr.get("resilience.bounded.calls", 0)
    b_to = ctr.get("resilience.bounded.timeouts", 0)
    b_strag = ctr.get("resilience.bounded.stragglers", 0)
    if r_open or r_rej or b_to or b_calls:
        print(
            f"resilience summary: breaker_opens={r_open} closes={r_close}"
            f" probes={r_probe} rejected={r_rej}"
            f" bounded_calls={b_calls} timeouts={b_to}"
            f" stragglers={b_strag}"
        )

    # one-line tracing health: how many distributed traces / trace-tagged
    # spans this run produced, flight-recorder traffic, and ring dumps
    # (assemble the actual timelines with cmd/ftstrace.py)
    tr = ctr.get("trace.traces", 0)
    fe = ctr.get("flight.events", 0)
    if tr or fe:
        print(
            f"trace summary: traces={tr}"
            f" spans={ctr.get('trace.spans', 0)}"
            f" recorder_events={fe}"
            f" dumps={ctr.get('flight.dumps', 0)}"
        )

    # one-line durability health: journal traffic, recovery/torn-tail
    # events, injected chaos, and client-side retry pressure
    wal_appends = ctr.get("wal.appends", 0)
    faults_injected = sum(
        v for k, v in ctr.items() if k.startswith("faults.injected.")
    )
    retries = sum(v for k, v in ctr.items() if k.startswith("remote.retry."))
    if wal_appends or faults_injected or retries or ctr.get("wal.recoveries", 0):
        print(
            f"durability summary: wal_appends={wal_appends}"
            f" replayed={ctr.get('wal.replayed.records', 0)}"
            f" torn_tails={ctr.get('wal.torn_tails', 0)}"
            f" snapshots={ctr.get('wal.snapshots', 0)}"
            f" recoveries={ctr.get('wal.recoveries', 0)}"
            f" faults_injected={faults_injected}"
            f" remote_retries={retries}"
        )

    # one-line state-plane health: vault traffic (tokens held / stored /
    # spent / certs dropped, journal appends+failures) and the selector's
    # p99 + lock-contention rate under concurrent spenders
    v_stored = ctr.get("vault.tokens.stored", 0)
    v_spent = ctr.get("vault.tokens.spent", 0)
    s_busy = ctr.get("selector.lock.busy", 0)
    s_acq = ctr.get("selector.lock.acquired", 0)
    if v_stored or v_spent or s_acq or ctr.get("vault.recoveries", 0):
        sel_h = d.get("histograms", {}).get("selector.select.seconds", {})
        busy_rate = s_busy / (s_busy + s_acq) if (s_busy + s_acq) else 0.0
        held = d.get("gauges", {}).get("vault.tokens.held", 0)
        print(
            f"state summary: tokens_held={int(held)}"
            f" stored={v_stored} spent={v_spent}"
            f" certs_dropped={ctr.get('vault.certs.dropped', 0)}"
            f" vault_appends={ctr.get('vault.appends', 0)}"
            f"(+{ctr.get('vault.append_failures', 0)} failed)"
            f" recoveries={ctr.get('vault.recoveries', 0)}"
            f" selector_p99="
            + ("-" if not sel_h.get("count") else _fmt_s(sel_h.get("p99", 0.0)))
            + f" lock_busy_rate={busy_rate:.2f}"
        )

    # one-line live-ops summary: queue/memory state at flush time plus
    # the latency quantiles the ops plane serves (p50/p95/p99)
    g = d.get("gauges", {})
    hh = d.get("histograms", {})
    commit_h = hh.get("ledger.block.commit.seconds", {})
    fin_h = hh.get("network.submit_to_finality.seconds", {})
    if ("orderer.queue.depth" in g or "ledger.inflight" in g
            or commit_h.get("count") or fin_h.get("count")):

        def _qs(h):
            if not h.get("count"):
                return "-"
            return "/".join(_fmt_s(h.get(q, 0.0)) for q in ("p50", "p95", "p99"))

        def _mb(v):
            return "-" if not v else f"{float(v) / 1e6:.1f}MB"

        print(
            f"ops summary: queue_depth={int(g.get('orderer.queue.depth', 0))}"
            f" inflight={int(g.get('ledger.inflight', 0))}"
            f" rss_peak={_mb(g.get('proc.rss.peak.bytes'))}"
            f" dev_mem_hw={_mb(g.get('stages.mem.high_water.bytes'))}"
            f" block_commit[p50/p95/p99]={_qs(commit_h)}"
            f" finality[p50/p95/p99]={_qs(fin_h)}"
        )

    # the slow-tx exemplar ring (`slo.exemplars` meta): the K slowest
    # submit->finality txs with their trace ids — paste one straight
    # into `ftstrace timeline`
    exemplars = meta.get("slo.exemplars")
    if isinstance(exemplars, list) and exemplars:
        print("\nslowest txs (submit->finality; trace with ftstrace timeline)")
        for row in exemplars:
            if not isinstance(row, (list, tuple)) or len(row) < 3:
                continue
            secs, tx, trace_id = row[0], row[1], row[2]
            print(f"  {_fmt_s(float(secs)):>8}  tx={tx}"
                  f"  trace={trace_id or '-'}")

    _print_kv(
        "gauges",
        sorted(d.get("gauges", {}).items()),
        fmt=lambda v: f"{v:g}",
    )

    hists = d.get("histograms", {})
    if hists:
        print("\nhistograms (count / mean / max / sum)")
        width = max(len(k) for k in hists)
        for name, h in sorted(hists.items()):
            if not h.get("count"):
                continue
            print(
                f"  {name:<{width}}  n={h['count']:<6} "
                f"mean={_fmt_s(h.get('mean', 0)):>8} "
                f"max={_fmt_s(h.get('max', 0)):>8} "
                f"sum={_fmt_s(h.get('sum', 0)):>8}"
            )

    spans = d.get("span_summary", {})
    if spans:
        print("\nspan summary (by total time)")
        width = max(len(k) for k in spans)
        for name, a in sorted(
            spans.items(), key=lambda kv: -kv[1].get("total_s", 0)
        ):
            print(
                f"  {name:<{width}}  n={a['count']:<6} "
                f"total={_fmt_s(a['total_s']):>8}"
            )


def diff(path_a: str, path_b: str) -> None:
    a, b = _load(path_a), _load(path_b)
    print(f"== {path_a} -> {path_b}")

    def _delta_rows(key, fmt_delta):
        names = sorted(set(a.get(key, {})) | set(b.get(key, {})))
        rows = []
        for n in names:
            va = a.get(key, {}).get(n, 0)
            vb = b.get(key, {}).get(n, 0)
            if va != vb:
                rows.append((n, fmt_delta(va, vb)))
        return rows

    _print_kv(
        "counters (old -> new)",
        _delta_rows("counters", lambda x, y: f"{x} -> {y}  ({y - x:+d})"),
    )
    _print_kv(
        "gauges (old -> new)",
        _delta_rows("gauges", lambda x, y: f"{x:g} -> {y:g}"),
    )

    ha, hb = a.get("histograms", {}), b.get("histograms", {})
    rows = []
    for n in sorted(set(ha) | set(hb)):
        ca = ha.get(n, {}).get("count", 0)
        cb = hb.get(n, {}).get("count", 0)
        sa = ha.get(n, {}).get("sum", 0.0)
        sb = hb.get(n, {}).get("sum", 0.0)
        if (ca, sa) != (cb, sb):
            rows.append(
                (n, f"n {ca} -> {cb}, sum {_fmt_s(sa)} -> {_fmt_s(sb)}")
            )
    _print_kv("histograms (old -> new)", rows)

    for label, d_ in (("old", a), ("new", b)):
        phases = d_.get("phases", [])
        if phases:
            line = ", ".join(
                f"{p['name']}={_fmt_s(p['elapsed_s'])}"
                for p in phases
                if "elapsed_s" in p
            )
            print(f"\nphases[{label}]: {line}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ftsmetrics", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_show = sub.add_parser("show", help="pretty-print one sidecar")
    p_show.add_argument("path")
    p_show.add_argument(
        "--prometheus",
        action="store_true",
        help="emit Prometheus text exposition instead of the human view",
    )
    p_diff = sub.add_parser("diff", help="diff two sidecars")
    p_diff.add_argument("old")
    p_diff.add_argument("new")
    args = ap.parse_args(argv)
    if args.cmd == "show":
        show(args.path, prometheus=args.prometheus)
    else:
        diff(args.old, args.new)
    return 0


if __name__ == "__main__":
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
    sys.exit(main())
