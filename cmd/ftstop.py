"""`top` for fts ledger nodes + the perf-regression observatory.

Usage:
    python cmd/ftstop.py top HOST:PORT [--interval S] [--count N | --once]
    python cmd/ftstop.py devices HOST:PORT [--interval S] [--count N | --once]
    python cmd/ftstop.py compare OLD.json NEW.json [--threshold F]
    python cmd/ftstop.py compare --history BENCH_history.jsonl [--last N]
    python cmd/ftstop.py compare --history BENCH_history.jsonl --scaling
    python cmd/ftstop.py compare --history BENCH_history.jsonl --soak
    python cmd/ftstop.py compare --history BENCH_history.jsonl --state
    python cmd/ftstop.py compare --history BENCH_history.jsonl --slo
    python cmd/ftstop.py compare --history BENCH_history.jsonl --device
    python cmd/ftstop.py compare --history BENCH_history.jsonl --host
    python cmd/ftstop.py compare --history BENCH_history.jsonl --failover

`top` polls a live node's ops RPCs (`ops.health` + `ops.metrics`, both
side-effect-free and commit-lock-free server-side) and renders one line
per poll: uptime, height, queue depth with its trend vs the previous
poll, in-flight txs, tx/s (counter delta between polls), backpressure
reject rate (`bp/s`), batched fraction, p95 block-commit and
submit→finality latency (bucket-interpolated quantiles computed
node-side), and process/device memory. Ctrl-C exits cleanly.

`devices` polls the same `ops.health` RPC and renders the device-plane
dispatch ledger (`utils/devobs.py`) as a per-program table: dispatches,
mean occupancy, padding waste %, p50/p99 dispatch wall, dp x mp
placement, compiles with their wall time, persistent-cache hits/misses,
and degrade decisions (breaker-open skips, dispatch-error fallbacks).

`compare` is the observatory: it diffs bench results against each other
or against the history file `bench.py` appends every outcome to
(`BENCH_history.jsonl`), using the shared result schema
(`fabric_token_sdk_tpu/utils/benchschema.py`). Per-metric verdicts are
threshold-based (default ±10%): throughput metrics regress when they
drop, cost metrics (`stage_warmup_s`, `wal_overhead_frac`) regress when
they grow. In history mode the baseline is the per-metric MEDIAN of the
prior valid rounds — one outlier round cannot poison the baseline. Exit
code 1 on any regression (CI-gateable; `--no-fail` disables).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import List, Optional, Tuple


def _repo_on_path() -> None:
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )


# ------------------------------------------------------------ top


def parse_address(s: str) -> Tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host or "127.0.0.1", int(port))


def _mb(v) -> str:
    return "-" if v in (None, 0) else f"{float(v) / 1e6:.1f}MB"


def _s(v) -> str:
    if v is None:
        return "-"
    return f"{v * 1000:.0f}ms" if v < 1 else f"{v:.2f}s"


def format_row(health: dict, snap: dict, prev_snap: Optional[dict],
               dt: Optional[float]) -> str:
    """One live-view line from an `ops.health` dict + `ops.metrics`
    snapshot (pure — unit-testable without a socket)."""
    ctr = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    rate = None
    if prev_snap is not None and dt and dt > 0:
        prev_valid = prev_snap.get("counters", {}).get("network.tx.valid", 0)
        rate = (ctr.get("network.tx.valid", 0) - prev_valid) / dt
    batched = ctr.get("ledger.validate.batched", 0)
    host_v = ctr.get("ledger.validate.host", 0)
    bfrac = batched / (batched + host_v) if (batched + host_v) else None
    # queue-depth trend (delta vs the previous poll's gauge) and the
    # backpressure reject rate — the two live signals of an admission-
    # controlled node under sustained load
    qd = health.get("queue_depth", 0)
    trend = ""
    if prev_snap is not None:
        prev_q = prev_snap.get("gauges", {}).get("orderer.queue.depth")
        if prev_q is not None:
            delta = qd - prev_q
            trend = f"({delta:+.0f})" if delta else "(=)"
    bp_rate = None
    if prev_snap is not None and dt and dt > 0:
        prev_bp = prev_snap.get("counters", {}).get(
            "orderer.backpressure.rejects", 0
        )
        bp_rate = (
            ctr.get("orderer.backpressure.rejects", 0) - prev_bp
        ) / dt

    def p95(name):
        return hists.get(name, {}).get("p95")

    parts = [
        f"up={health.get('uptime_s', 0):.0f}s",
        f"height={health.get('height', 0)}",
        f"queue={qd}{trend}",
        f"inflight={health.get('inflight', 0)}",
        "tx/s=" + ("-" if rate is None else f"{rate:.2f}"),
        "bp/s=" + ("-" if bp_rate is None else f"{bp_rate:.2f}"),
        "batched=" + ("-" if bfrac is None else f"{bfrac:.0%}"),
        f"p95.commit={_s(p95('ledger.block.commit.seconds'))}",
        f"p95.finality={_s(p95('network.submit_to_finality.seconds'))}",
        f"rss={_mb(gauges.get('proc.rss.bytes'))}",
        f"dev_mem={_mb(gauges.get('device.mem.bytes'))}",
    ]
    # circuit-breaker column (resilience layer): `brk=ok` while every
    # plane that ever dispatched is closed, else the degraded planes and
    # their states — the live "a device plane is riding its host
    # fallback" signal. Absent entirely on nodes predating the field.
    breakers = health.get("breakers")
    if breakers is not None:
        degraded = {p: s for p, s in breakers.items() if s != "closed"}
        parts.append(
            "brk="
            + (",".join(f"{p}:{s}" for p, s in sorted(degraded.items()))
               if degraded else "ok")
        )
    # SLO column: `slo=ok` while every error budget has headroom, else
    # the breaching SLOs with their burn (budget multiples consumed) —
    # the "we are eating tomorrow's reliability" signal. Absent on nodes
    # predating the SLO engine.
    slo_sec = health.get("slo")
    if isinstance(slo_sec, dict):
        rows = slo_sec.get("slos", {})
        breaching = {
            name: r for name, r in rows.items()
            if isinstance(r, dict) and r.get("ok") is False
        }
        parts.append(
            "slo="
            + (",".join(
                f"{name}!{r.get('burn', 0):.1f}x"
                for name, r in sorted(breaching.items())
            ) if breaching else "ok")
        )
    # replication column: the node's place in the replicated plane —
    # `repl=leader@e3 lag=0` (worst follower lag) on a leader,
    # `repl=follower@e3 lag=2` (blocks behind the shipped stream) on a
    # follower. Absent on standalone nodes and nodes predating the
    # replication plane (health carries no `repl` section).
    repl = health.get("repl")
    if isinstance(repl, dict):
        parts.append(
            f"repl={repl.get('role', '?')}@e{repl.get('epoch', '?')} "
            f"lag={repl.get('lag', '-')}"
        )
    wal = health.get("wal")
    if wal:
        parts.append(
            f"wal={_mb(wal.get('bytes'))}"
            + (" POISONED" if wal.get("poisoned") else "")
        )
    lb = health.get("last_block")
    if lb:
        bd = lb.get("breakdown", {})
        parts.append(
            f"last_block=#{lb.get('number')}[{lb.get('txs')}tx "
            f"{_s(lb.get('commit_s'))}"
            f" dev={_s(bd.get('device_verify_s'))}"
            f" sign={_s(bd.get('sign_verify_s'))}"
            f" wal={_s(bd.get('wal_s'))}]"
        )
    return "  ".join(parts)


def top(address, interval: float = None, count: Optional[int] = None,
        out=None) -> int:
    """Poll a node's ops plane and print one line per poll."""
    from fabric_token_sdk_tpu.services.network.remote import RemoteNetwork

    if interval is None:
        interval = float(os.environ.get("FTS_OPS_INTERVAL_S", "2"))
    out = out if out is not None else sys.stdout
    addr = parse_address(address) if isinstance(address, str) else tuple(address)
    net = RemoteNetwork(addr)
    prev_snap, prev_t = None, None
    i = 0
    try:
        while count is None or i < count:
            if i:
                time.sleep(interval)
            health = net.ops_health()
            snap = net.ops_metrics()
            now = time.monotonic()
            dt = (now - prev_t) if prev_t is not None else None
            print(format_row(health, snap, prev_snap, dt), file=out, flush=True)
            prev_snap, prev_t = snap, now
            i += 1
    except KeyboardInterrupt:
        pass
    finally:
        net.close()
    return 0


# ------------------------------------------------------------ devices


def _pct(v) -> str:
    return "-" if v is None else f"{v:.1%}"


def format_devices(health: dict) -> str:
    """The per-program device-plane table from an `ops.health` dict
    (pure — unit-testable without a socket). One header line with the
    per-plane occupancy roll-up, one row per (plane, program)."""
    dev = health.get("device")
    if not isinstance(dev, dict):
        return "devices: node predates the dispatch ledger"
    planes = dev.get("planes") or {}
    programs = dev.get("programs") or {}
    head = "planes: " + (
        "  ".join(
            f"{name}[n={p.get('dispatches', 0)} "
            f"occ={_pct(p.get('occupancy'))} "
            f"waste={_pct(p.get('waste_frac'))}]"
            for name, p in sorted(planes.items())
        ) if planes else "(no dispatches yet)"
    )
    if not programs:
        return head
    lines = [head]
    cols = (
        f"{'plane':<8} {'program':<20} {'disp':>6} {'occ':>7} "
        f"{'waste':>7} {'p50':>9} {'p99':>9} {'dpxmp':>6} "
        f"{'compiles':>8} {'comp_s':>7} {'hit/miss':>9} {'degr':>5}"
    )
    lines.append(cols)
    for _key, r in sorted(programs.items()):
        lines.append(
            f"{r.get('plane', '-'):<8} {r.get('program', '-'):<20} "
            f"{r.get('dispatches', 0):>6} {_pct(r.get('occupancy')):>7} "
            f"{_pct(r.get('waste_frac')):>7} {_s(r.get('p50_s')):>9} "
            f"{_s(r.get('p99_s')):>9} "
            f"{r.get('dp', 1)}x{r.get('mp', 1):<3} "
            f"{r.get('compiles', 0):>8} {r.get('compile_s', 0):>7g} "
            f"{r.get('cache_hits', 0)}/{r.get('cache_misses', 0):<4} "
            f"{r.get('degrades', 0):>5}"
        )
    return "\n".join(lines)


def devices(address, interval: float = None, count: Optional[int] = None,
            out=None) -> int:
    """Poll a node's ops plane and print the device ledger per poll."""
    from fabric_token_sdk_tpu.services.network.remote import RemoteNetwork

    if interval is None:
        interval = float(os.environ.get("FTS_OPS_INTERVAL_S", "2"))
    out = out if out is not None else sys.stdout
    addr = parse_address(address) if isinstance(address, str) else tuple(address)
    net = RemoteNetwork(addr)
    i = 0
    try:
        while count is None or i < count:
            if i:
                time.sleep(interval)
            print(format_devices(net.ops_health()), file=out, flush=True)
            i += 1
    except KeyboardInterrupt:
        pass
    finally:
        net.close()
    return 0


# ------------------------------------------------------------ compare

# (result-JSON field, direction): +1 = higher is better, -1 = lower is
COMPARE_METRICS = (
    ("value", +1),
    ("block_txs_per_s", +1),
    ("prove_txs_per_s", +1),
    ("block_provegen_txs_per_s", +1),
    ("stage_warmup_s", -1),
    ("wal_overhead_frac", -1),
)


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def compare_records(old: dict, new: dict, threshold: float = 0.1) -> List[dict]:
    """Per-metric verdicts between two bench results: `regression` /
    `improvement` when the direction-adjusted relative change exceeds
    `threshold`, else `ok`. Metrics missing from either side are
    skipped — a degraded round simply compares on fewer metrics."""
    degraded = bool(old.get("degraded")) or bool(new.get("degraded"))
    verdicts = []
    for key, direction in COMPARE_METRICS:
        if degraded and direction < 0:
            # a deadline-truncated run's cost metrics are partial by
            # definition (it died mid-phase) — comparing them yields
            # spurious "improvements"; throughput drops are the signal
            continue
        a, b = old.get(key), new.get(key)
        if not _num(a) or not _num(b):
            continue
        if a == 0 and b == 0:
            rel = 0.0
        elif a == 0:
            rel = float("inf") if b > 0 else float("-inf")
        else:
            rel = (b - a) / abs(a)
        score = rel * direction
        verdict = (
            "regression" if score < -threshold
            else "improvement" if score > threshold
            else "ok"
        )
        verdicts.append({
            "metric": key,
            "old": a,
            "new": b,
            "change_frac": rel if abs(rel) != float("inf") else None,
            "verdict": verdict,
        })
    return verdicts


def scaling_curve(result: dict) -> Optional[List[dict]]:
    """The schema-valid throughput-vs-devices curve of one bench result,
    or None (rounds predating the scaling sweep, invalid rows, or a
    degenerate single-point curve — gating at n=1 would always pass,
    since efficiency there is 1.0 by construction)."""
    from fabric_token_sdk_tpu.utils import benchschema

    c = result.get("scaling")
    if (
        isinstance(c, list) and len(c) >= 2
        and not benchschema.validate_scaling(c)
    ):
        return c
    return None


def efficiency_at(curve: List[dict], n_devices: int) -> Optional[float]:
    for row in curve:
        if row.get("n_devices") == n_devices:
            return row.get("efficiency")
    return None


def compare_scaling(args) -> int:
    """The scaling observatory: report the latest round's
    throughput-vs-devices curve and gate on per-device efficiency at the
    MAX device count — the number that says whether adding devices still
    pays. Baseline = median efficiency at the same device count over the
    prior rounds that measured it. Exit 1 when it regresses by more than
    the threshold (CI-gateable; `--no-fail` disables), 2 when fewer than
    two rounds carry a curve."""
    from fabric_token_sdk_tpu.utils import benchschema

    rows = benchschema.load_history(args.history)
    curves = []
    for row in rows:
        result = benchschema.extract_result(row)
        if not result or benchschema.validate_result(result):
            continue
        c = scaling_curve(result)
        if c:
            curves.append(c)
    if args.last:
        curves = curves[-args.last:]
    if len(curves) < 2:
        print(
            "ftstop compare --scaling: need at least 2 history rounds with "
            f"a scaling curve, found {len(curves)}", file=sys.stderr,
        )
        return 2
    latest, prior = curves[-1], curves[:-1]
    max_n = latest[-1]["n_devices"]
    print(f"== scaling curve, latest round (threshold ±{args.threshold:.0%})")
    for row in latest:
        print(
            f"   n_devices={row['n_devices']:<3} "
            f"block_txs_per_s={row['block_txs_per_s']:<10g} "
            f"efficiency={row['efficiency']:.0%}"
        )
    base_vals = [
        e for e in (efficiency_at(c, max_n) for c in prior) if _num(e)
    ]
    if not base_vals:
        print(
            f"ftstop compare --scaling: no prior round measured "
            f"{max_n} devices — nothing to gate against", file=sys.stderr,
        )
        return 2
    base = statistics.median(base_vals)
    new = latest[-1]["efficiency"]
    rel = (new - base) / abs(base) if base else 0.0
    verdict = (
        "regression" if rel < -args.threshold
        else "improvement" if rel > args.threshold
        else "ok"
    )
    print(
        f"{verdict.upper():<12} efficiency@{max_n}dev "
        f"{base:g} -> {new:g}  ({rel:+.1%}, "
        f"median of {len(base_vals)} prior round(s))"
    )
    return 1 if verdict == "regression" and not args.no_fail else 0


def soak_of(result: dict) -> Optional[dict]:
    """The `soak` section of one schema-valid bench result, or None.
    (Callers filter through `validate_result` first, which already
    field-checks any dict-typed soak section — no re-validation here.)"""
    s = result.get("soak")
    return s if isinstance(s, dict) else None


# (soak field, direction): +1 = higher is better, -1 = lower is better
SOAK_METRICS = (
    ("steady_txs_per_s", +1),
    ("p99_finality_s", -1),
)


def _gate_sections(args, section_name, section_of, metrics,
                   header) -> int:
    """Shared engine of the section observatories (`--soak`/`--state`):
    collect the named section from every schema-valid history round,
    gate the latest against the per-metric MEDIAN of the prior
    section-carrying rounds with direction-aware threshold verdicts.
    Exit 1 on regression (CI-gateable; `--no-fail` disables), 2 when
    fewer than two rounds carry the section or nothing compares."""
    from fabric_token_sdk_tpu.utils import benchschema

    rows = benchschema.load_history(args.history)
    sections = []
    for row in rows:
        result = benchschema.extract_result(row)
        if not result or benchschema.validate_result(result):
            continue
        s = section_of(result)
        if s:
            sections.append(s)
    if args.last:
        sections = sections[-args.last:]
    if len(sections) < 2:
        print(
            f"ftstop compare --{section_name}: need at least 2 history "
            f"rounds with a {section_name} section, found {len(sections)}",
            file=sys.stderr,
        )
        return 2
    latest, prior = sections[-1], sections[:-1]
    print(f"== {header(latest)}  (threshold ±{args.threshold:.0%})")
    regressions = 0
    compared = 0
    width = max(len(k) for k, _d in metrics)
    for key, direction in metrics:
        base_vals = [s[key] for s in prior if _num(s.get(key))]
        new = latest.get(key)
        if not base_vals or not _num(new):
            continue
        base = statistics.median(base_vals)
        rel = (new - base) / abs(base) if base else 0.0
        score = rel * direction
        verdict = (
            "regression" if score < -args.threshold
            else "improvement" if score > args.threshold
            else "ok"
        )
        compared += 1
        if verdict == "regression":
            regressions += 1
        print(
            f"{verdict.upper():<12} {section_name}.{key:<{width}} "
            f"{base:g} -> {new:g}  ({rel:+.1%}, "
            f"median of {len(base_vals)} prior round(s))"
        )
    if not compared:
        print(f"ftstop compare --{section_name}: no comparable "
              f"{section_name} metrics", file=sys.stderr)
        return 2
    return 1 if regressions and not args.no_fail else 0


def compare_soak(args) -> int:
    """The soak observatory: gate on the sustained-load numbers —
    steady-state tx/s regresses when it drops, p99 finality when it
    grows — against the per-metric MEDIAN of the prior soak-carrying
    history rounds (same pattern as `--scaling`)."""
    return _gate_sections(
        args, "soak", soak_of, SOAK_METRICS,
        lambda s: (
            f"soak, latest round: steady={s['steady_txs_per_s']:g}tx/s "
            f"p99_finality={s.get('p99_finality_s')} "
            f"queue_max={s['queue_depth_max']:g} "
            f"backpressure={s['backpressure_rejects']} "
            f"driver={s.get('driver', 'fabtoken')} "
            f"sign={s.get('sign_plane', '-')} "
            f"host_validate_frac={s.get('host_validate_frac', '-')} "
            f"faults={s.get('faults_injected', 0)} "
            f"breaker_trips={s.get('breaker_trips', 0)} "
            f"degraded_planes={s.get('degraded_planes', 0)}"
        ),
    )


def state_of(result: dict) -> Optional[dict]:
    """The `state` section of one schema-valid bench result, or None.
    (Callers filter through `validate_result` first, which already
    field-checks any dict-typed state section.)"""
    s = result.get("state")
    return s if isinstance(s, dict) else None


# (state field, direction): +1 = higher is better, -1 = lower is better
STATE_METRICS = (
    ("selector_p99_s", -1),
    ("populate_tokens_per_s", +1),
    ("recover_tokens_per_s", +1),
)


def compare_state(args) -> int:
    """The state-plane observatory: gate the client state plane's scale
    numbers — selection p99 under concurrent spenders regresses when it
    GROWS, steady populate/recover throughput when it DROPS — against
    the per-metric MEDIAN of the prior state-carrying history rounds
    (same contract as `--scaling`/`--soak`)."""
    return _gate_sections(
        args, "state", state_of, STATE_METRICS,
        lambda s: (
            f"state plane, latest round: tokens={s['tokens']} "
            f"selector_p99={s['selector_p99_s']:g}s "
            f"populate={s['populate_tokens_per_s']:g}tok/s "
            f"recover={s['recover_tokens_per_s']:g}tok/s "
            f"rss_hw={s['rss_high_water_mb']:g}MB"
        ),
    )


def device_of(result: dict) -> Optional[dict]:
    """The `device` section of one schema-valid bench result, or None.
    (Callers filter through `validate_result` first, which already
    field-checks any dict-typed device section.)"""
    s = result.get("device")
    return s if isinstance(s, dict) else None


# (device field, direction): +1 = higher is better, -1 = lower is better
DEVICE_METRICS = (
    ("occupancy", +1),
    ("waste_frac", -1),
    ("dispatch_p99_s", -1),
)


def compare_device(args) -> int:
    """The device-plane observatory: gate the dispatch ledger's
    efficiency numbers — batch occupancy regresses when it DROPS,
    padding waste and p99 dispatch wall when they GROW — against the
    per-metric MEDIAN of the prior device-carrying history rounds (same
    contract as `--scaling`/`--soak`/`--state`)."""
    return _gate_sections(
        args, "device", device_of, DEVICE_METRICS,
        lambda s: (
            f"device plane, latest round: dispatches={s['dispatches']} "
            f"occupancy={s.get('occupancy')} "
            f"waste={s.get('waste_frac')} "
            f"p99={s.get('dispatch_p99_s')}s "
            f"compiles={s.get('compiles', 0)} "
            f"degrades={s.get('degrades', 0)} "
            f"planes={','.join(sorted((s.get('planes') or {})))}"
        ),
    )


def host_of(result: dict) -> Optional[dict]:
    """The `host` section of one schema-valid bench result, or None.
    (Callers filter through `validate_result` first, which already
    field-checks any dict-typed host section.)"""
    s = result.get("host")
    return s if isinstance(s, dict) else None


# (host field, direction): +1 = higher is better, -1 = lower is better
HOST_METRICS = (
    ("host_validate_frac", -1),
    ("unmarshal_p99_s", -1),
    ("fiat_shamir_p99_s", -1),
)


def compare_host(args) -> int:
    """The host-path observatory: gate the batch-first host validation
    numbers — the host leg's fraction of block commit wall and the
    per-block unmarshal / fiat_shamir p99s regress when they GROW —
    against the per-metric MEDIAN of the prior host-carrying history
    rounds (same contract as `--scaling`/`--soak`/`--device`)."""
    return _gate_sections(
        args, "host", host_of, HOST_METRICS,
        lambda s: (
            f"host path, latest round: "
            f"host_validate_frac={s.get('host_validate_frac')} "
            f"unmarshal={s['unmarshal_s']:g}s "
            f"fiat_shamir={s['fiat_shamir_s']:g}s "
            f"sig_verify={s['sig_verify_s']:g}s "
            f"batch_rows={s.get('sign_batch_rows', 0)}/"
            f"{s.get('proof_batch_rows', 0)}/"
            f"{s.get('conservation_rows', 0)} "
            f"req_cache={s.get('request_cache_hit_rate')} "
            f"parse_cache={s.get('parse_cache_hit_rate')} "
            f"workers={s.get('workers', '-')}"
        ),
    )


def failover_of(result: dict) -> Optional[dict]:
    """The `failover` section of one schema-valid bench result, or None.
    (Callers filter through `validate_result` first, which already
    field-checks any dict-typed failover section.)"""
    s = result.get("failover")
    return s if isinstance(s, dict) else None


# (failover field, direction): +1 = higher is better, -1 = lower better
FAILOVER_METRICS = (
    ("acked_tx_loss", -1),
    ("duplicate_commits", -1),
    ("failover_p99_s", -1),
    ("follower_lag_max", -1),
)


def compare_failover(args) -> int:
    """The replication observatory: gate the kill-the-leader chaos-soak
    contract. Two verdicts layered: the LOSS metrics (`acked_tx_loss`,
    `duplicate_commits`) are ABSOLUTE — any nonzero value in the latest
    round is a regression regardless of the baseline, because the
    relative engine's `(new - base) / base` arithmetic treats a 0 -> 1
    jump on a zero baseline as 0% change and would wave the one
    regression this gate exists to catch straight through. The latency
    metrics (`failover_p99_s`, `follower_lag_max`) gate relatively
    against the median of prior failover-carrying rounds, same contract
    as `--soak`/`--host`."""
    rc = _gate_sections(
        args, "failover", failover_of, FAILOVER_METRICS,
        lambda s: (
            f"failover, latest round: acked={s.get('acked_txs', '-')} "
            f"loss={s['acked_tx_loss']} dups={s['duplicate_commits']} "
            f"p99={s.get('failover_p99_s')}s "
            f"lag_max={s['follower_lag_max']:g} "
            f"epoch={s.get('promoted_epoch', '-')} "
            f"promotion={s.get('promotion', '-')} "
            f"switches={s.get('failover_switches', 0)}"
        ),
    )
    if rc == 2:
        return rc
    # the absolute layer: zero-tolerance on the correctness metrics
    from fabric_token_sdk_tpu.utils import benchschema

    sections = []
    for row in benchschema.load_history(args.history):
        result = benchschema.extract_result(row)
        if not result or benchschema.validate_result(result):
            continue
        s = failover_of(result)
        if s:
            sections.append(s)
    if args.last:
        sections = sections[-args.last:]
    hard = 0
    for key in ("acked_tx_loss", "duplicate_commits"):
        v = sections[-1].get(key) if sections else None
        if _num(v) and v > 0:
            hard += 1
            print(f"REGRESSION   failover.{key:<17} {v:g}  "
                  "(absolute: any nonzero value fails the gate)")
    if hard:
        return 1 if not args.no_fail else rc
    return rc


def compare_slo(args) -> int:
    """The SLO gate: unlike the regression observatories (which diff
    against prior rounds), this is an ABSOLUTE verdict on the latest
    history round that carries an `slo` section — the declared
    objectives ARE the baseline. Exit 1 when any error budget is
    exhausted (`ok: false`; CI-gateable, `--no-fail` disables), 2 when
    no round carries the section, 0 when every budget has headroom."""
    from fabric_token_sdk_tpu.utils import benchschema

    rows = benchschema.load_history(args.history)
    sections = []
    for row in rows:
        result = benchschema.extract_result(row)
        if not result or benchschema.validate_result(result):
            continue
        s = result.get("slo")
        if isinstance(s, dict) and isinstance(s.get("slos"), dict):
            sections.append(s)
    if args.last:
        sections = sections[-args.last:]
    if not sections:
        print(
            "ftstop compare --slo: no history round carries an slo "
            "section", file=sys.stderr,
        )
        return 2
    latest = sections[-1]
    print(f"== slo verdict, latest round (window {latest.get('window_s')}s)")
    breaches = 0
    for name, r in sorted(latest["slos"].items()):
        if not isinstance(r, dict):
            continue
        ok = r.get("ok") is not False
        if not ok:
            breaches += 1
        target = r.get("target_s")
        print(
            f"{'OK' if ok else 'BREACH':<12} {name:<16} "
            f"objective={r.get('objective')}"
            + (f"@{target:g}s" if _num(target) else "")
            + f" good_frac={r.get('good_frac')}"
            f" burn={r.get('burn')}x"
            f" budget_remaining={r.get('budget_remaining')}"
            f" n={r.get('total')}"
        )
    print(
        f"verdict: {breaches} breached error budget(s) of "
        f"{len(latest['slos'])}"
    )
    return 1 if breaches and not args.no_fail else 0


def baseline_of(records: List[dict]) -> dict:
    """Per-metric median over a set of valid rounds — the history-mode
    baseline (one outlier round cannot poison it)."""
    base = {}
    for key, _dir in COMPARE_METRICS:
        vals = [r[key] for r in records if _num(r.get(key))]
        if vals:
            base[key] = statistics.median(vals)
    return base


def compare(args) -> int:
    from fabric_token_sdk_tpu.utils import benchschema

    if args.history:
        rows = benchschema.load_history(args.history)
        valid = []
        for i, row in enumerate(rows):
            result = benchschema.extract_result(row)
            problems = benchschema.validate_result(result)
            if problems:
                print(
                    f"[ftstop] {args.history} line {i + 1} fails the bench "
                    f"schema ({problems[0]}) — skipped",
                    file=sys.stderr,
                )
                continue
            valid.append(result)
        if args.last:
            valid = valid[-args.last:]
        if len(valid) < 2:
            print("ftstop compare: need at least 2 schema-valid history "
                  f"records, found {len(valid)}", file=sys.stderr)
            return 2
        # degraded rounds are truncated OUTCOMES, not baselines: their
        # zero/partial metrics would drag the median toward 0 and turn a
        # real regression into an "improvement". The LATEST round still
        # compares whatever it is — a degraded latest is exactly the
        # alert the observatory exists to raise.
        prior = [r for r in valid[:-1] if not r.get("degraded")]
        if not prior:
            print("ftstop compare: no full (non-degraded) prior rounds to "
                  "baseline against", file=sys.stderr)
            return 2
        old, new = baseline_of(prior), valid[-1]
        old_label = f"median({len(prior)} prior full rounds)"
        new_label = "latest round"
    else:
        old = benchschema.load_result(args.old)
        new = benchschema.load_result(args.new)
        for path, result in ((args.old, old), (args.new, new)):
            problems = benchschema.validate_result(result)
            if problems:
                print(
                    f"[ftstop] {path} fails the bench schema: "
                    + "; ".join(problems),
                    file=sys.stderr,
                )
                return 2
        old_label, new_label = args.old, args.new
    print(f"== {old_label} -> {new_label}  (threshold ±{args.threshold:.0%})")
    for rec, label in ((old, old_label), (new, new_label)):
        if rec.get("degraded"):
            print(f"   note: {label} is a DEGRADED result "
                  f"(died in phase {rec.get('phase', '?')!r})")
    verdicts = compare_records(old, new, args.threshold)
    if not verdicts:
        print("no comparable metrics between the two records")
        return 2
    for v in verdicts:
        chg = "n/a" if v["change_frac"] is None else f"{v['change_frac']:+.1%}"
        print(
            f"{v['verdict'].upper():<12} {v['metric']:<26} "
            f"{v['old']:g} -> {v['new']:g}  ({chg})"
        )
    regressions = [v for v in verdicts if v["verdict"] == "regression"]
    improvements = [v for v in verdicts if v["verdict"] == "improvement"]
    print(
        f"verdict: {len(regressions)} regression(s), "
        f"{len(improvements)} improvement(s), "
        f"{len(verdicts) - len(regressions) - len(improvements)} ok"
    )
    return 1 if regressions and not args.no_fail else 0


# ------------------------------------------------------------ main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ftstop", description=__doc__.splitlines()[0]
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_top = sub.add_parser("top", help="live ops view of a running node")
    p_top.add_argument("address", help="HOST:PORT of a LedgerServer")
    p_top.add_argument("--interval", type=float, default=None,
                       help="poll interval seconds (FTS_OPS_INTERVAL_S)")
    p_top.add_argument("--count", type=int, default=None,
                       help="stop after N polls (default: forever)")
    p_top.add_argument("--once", action="store_true",
                       help="one poll, then exit (same as --count 1)")
    p_dev = sub.add_parser(
        "devices",
        help="per-program device dispatch ledger of a running node",
    )
    p_dev.add_argument("address", help="HOST:PORT of a LedgerServer")
    p_dev.add_argument("--interval", type=float, default=None,
                       help="poll interval seconds (FTS_OPS_INTERVAL_S)")
    p_dev.add_argument("--count", type=int, default=None,
                       help="stop after N polls (default: forever)")
    p_dev.add_argument("--once", action="store_true",
                       help="one poll, then exit (same as --count 1)")
    p_cmp = sub.add_parser("compare", help="diff bench rounds for regressions")
    p_cmp.add_argument("old", nargs="?", help="old result/round JSON")
    p_cmp.add_argument("new", nargs="?", help="new result/round JSON")
    p_cmp.add_argument("--history", help="BENCH_history.jsonl observatory file")
    p_cmp.add_argument("--last", type=int, default=None,
                       help="history mode: only consider the last N rounds")
    p_cmp.add_argument("--threshold", type=float, default=0.1,
                       help="relative change that counts as a verdict")
    # one gate mode per invocation: a silently-ignored second flag would
    # let its regression pass CI unreported
    p_gate = p_cmp.add_mutually_exclusive_group()
    p_gate.add_argument("--scaling", action="store_true",
                        help="gate on the throughput-vs-devices curve: "
                             "per-device efficiency at the max device count "
                             "(history mode only)")
    p_gate.add_argument("--soak", action="store_true",
                        help="gate on the sustained-load soak: steady-state "
                             "tx/s and p99 finality vs the median of prior "
                             "soak-carrying rounds (history mode only)")
    p_gate.add_argument("--state", action="store_true",
                        help="gate on the state-plane scale numbers: selector "
                             "p99 (growth) and populate/recover throughput "
                             "(drop) vs the median of prior state-carrying "
                             "rounds (history mode only)")
    p_gate.add_argument("--slo", action="store_true",
                        help="gate on the latest round's SLO verdict: exit 1 "
                             "when any error budget is exhausted — absolute, "
                             "not relative to prior rounds (history mode "
                             "only)")
    p_gate.add_argument("--device", action="store_true",
                        help="gate on the device-plane dispatch ledger: batch "
                             "occupancy (drop), padding waste and p99 "
                             "dispatch wall (growth) vs the median of prior "
                             "device-carrying rounds (history mode only)")
    p_gate.add_argument("--host", action="store_true",
                        help="gate on the batch-first host path: host-leg "
                             "fraction of commit wall and unmarshal / "
                             "fiat_shamir p99 (growth) vs the median of "
                             "prior host-carrying rounds (history mode only)")
    p_gate.add_argument("--failover", action="store_true",
                        help="gate on the kill-the-leader chaos soak: "
                             "acked-tx loss and duplicate commits "
                             "(absolute — any nonzero fails), failover p99 "
                             "and follower lag (growth) vs the median of "
                             "prior failover-carrying rounds (history mode "
                             "only)")
    p_cmp.add_argument("--no-fail", action="store_true",
                       help="exit 0 even when regressions are flagged")
    args = ap.parse_args(argv)
    if args.cmd == "top":
        return top(args.address, args.interval,
                   1 if args.once else args.count)
    if args.cmd == "devices":
        return devices(args.address, args.interval,
                       1 if args.once else args.count)
    if args.scaling:
        if not args.history:
            ap.error("compare --scaling needs --history")
        return compare_scaling(args)
    if args.soak:
        if not args.history:
            ap.error("compare --soak needs --history")
        return compare_soak(args)
    if args.state:
        if not args.history:
            ap.error("compare --state needs --history")
        return compare_state(args)
    if args.slo:
        if not args.history:
            ap.error("compare --slo needs --history")
        return compare_slo(args)
    if args.device:
        if not args.history:
            ap.error("compare --device needs --history")
        return compare_device(args)
    if args.host:
        if not args.history:
            ap.error("compare --host needs --history")
        return compare_host(args)
    if args.failover:
        if not args.history:
            ap.error("compare --failover needs --history")
        return compare_failover(args)
    if not args.history and (not args.old or not args.new):
        ap.error("compare needs OLD and NEW files, or --history")
    return compare(args)


if __name__ == "__main__":
    _repo_on_path()
    sys.exit(main())
