"""Host BN254 math correctness: group laws, twist, pairing bilinearity."""
import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm


def test_curve_constants():
    # p and r are BN primes derived from u
    u = hm.U
    assert hm.P == 36 * u**4 + 36 * u**3 + 24 * u**2 + 6 * u + 1
    assert hm.R == 36 * u**4 + 36 * u**3 + 18 * u**2 + 6 * u + 1
    assert hm.g1_is_on_curve(hm.G1_GEN)
    assert hm.g2_is_on_curve(hm.G2_GEN)


def test_g1_group_law(rng):
    g = hm.G1_GEN
    assert hm.g1_mul(g, hm.R) is None  # order r
    a, b = hm.rand_zr(rng), hm.rand_zr(rng)
    left = hm.g1_mul(g, (a + b) % hm.R)
    right = hm.g1_add(hm.g1_mul(g, a), hm.g1_mul(g, b))
    assert left == right
    assert hm.g1_add(left, hm.g1_neg(left)) is None


def test_g2_group_law(rng):
    q = hm.G2_GEN
    assert hm.g2_mul(q, hm.R) is None  # subgroup order r
    a, b = hm.rand_zr(rng), hm.rand_zr(rng)
    assert hm.g2_mul(q, (a + b) % hm.R) == hm.g2_add(hm.g2_mul(q, a), hm.g2_mul(q, b))


def test_fp2_fp12_field(rng):
    a = hm.fp2(rng.randrange(hm.P), rng.randrange(hm.P))
    assert hm.fp2_mul(a, hm.fp2_inv(a)) == hm.FP2_ONE
    x = tuple(hm.fp2(rng.randrange(hm.P), rng.randrange(hm.P)) for _ in range(6))
    assert hm.fp12_mul(x, hm.fp12_inv(x)) == hm.FP12_ONE
    # frobenius is the p-power map
    assert hm.fp12_frobenius(x) == hm.fp12_pow(x, hm.P)


@pytest.mark.slow
def test_pairing_bilinear():
    p, q = hm.G1_GEN, hm.G2_GEN
    e = hm.pairing(p, q)
    assert e != hm.FP12_ONE  # non-degenerate
    assert hm.fp12_pow(e, hm.R) == hm.FP12_ONE  # in the r-torsion of GT
    a, b = 17, 29
    e_ab = hm.pairing(hm.g1_mul(p, a), hm.g2_mul(q, b))
    assert e_ab == hm.fp12_pow(e, a * b)


@pytest.mark.slow
def test_pairing_product_unity():
    # e(aP, Q) * e(-P, aQ) == 1
    a = 123456789
    one = hm.pairing_product(
        [
            (hm.g1_mul(hm.G1_GEN, a), hm.G2_GEN),
            (hm.g1_neg(hm.G1_GEN), hm.g2_mul(hm.G2_GEN, a)),
        ]
    )
    assert hm.gt_is_unity(one)


def test_encodings_roundtrip(rng):
    pt = hm.rand_g1(rng)
    assert hm.g1_from_bytes(hm.g1_to_bytes(pt)) == pt
    assert hm.g1_from_bytes(hm.g1_to_bytes(None)) is None
    q = hm.rand_g2(rng)
    assert hm.g2_from_bytes(hm.g2_to_bytes(q)) == q
    z = hm.rand_zr(rng)
    assert hm.zr_from_bytes(hm.zr_to_bytes(z)) == z


def test_hash_to_zr_and_g1():
    z1 = hm.hash_to_zr(b"hello")
    z2 = hm.hash_to_zr(b"hello")
    assert z1 == z2 and 0 <= z1 < hm.R
    assert hm.hash_to_zr(b"world") != z1
    pt = hm.hash_to_g1(b"hello")
    assert hm.g1_is_on_curve(pt)
    assert pt == hm.hash_to_g1(b"hello")


def test_noncanonical_encodings_rejected(rng):
    pt = hm.rand_g1()
    raw = bytearray(hm.g1_to_bytes(pt))
    # coordinate >= P
    big = bytearray(b"\x00" + ((pt[0] + hm.P).to_bytes(32, "big")) + pt[1].to_bytes(32, "big"))
    with pytest.raises(ValueError):
        hm.g1_from_bytes(bytes(big))
    # bad tag
    raw[0] = 7
    with pytest.raises(ValueError):
        hm.g1_from_bytes(bytes(raw))
    # non-canonical infinity
    with pytest.raises(ValueError):
        hm.g1_from_bytes(b"\x01" + b"\x00" * 63 + b"\x02")
    with pytest.raises(ValueError):
        hm.g1_from_bytes(b"\x00" * 10)


def test_g2_subgroup_check(rng):
    # random on-curve twist point is (w.h.p.) outside the r-subgroup
    while True:
        x = (rng.randrange(hm.P), rng.randrange(hm.P))
        y = hm.fp2_sqrt(hm.fp2_add(hm.fp2_mul(hm.fp2_sqr(x), x), hm.B2))
        if y is not None:
            pt = (x, y)
            break
    assert hm.g2_is_on_curve(pt)
    assert not hm.g2_in_subgroup(pt)
    with pytest.raises(ValueError):
        hm.g2_from_bytes(hm.g2_to_bytes(pt))


def test_multiexp_length_mismatch(rng):
    with pytest.raises(ValueError):
        hm.g1_multiexp([hm.G1_GEN], [1, 2])
    with pytest.raises(ValueError):
        hm.g2_multiexp([hm.G2_GEN, hm.G2_GEN], [1])
