"""Chaos suite: WAL durability, crash recovery, fault-hardened remote path.

Proves the PR-7 invariants under injected faults (`utils/faults.py`):

* the WAL journal survives torn tails and CRC corruption (truncate, never
  crash);
* `Network.recover` rebuilds snapshot + WAL-suffix state exactly — a
  block a submitter ever saw finality for is never lost, a double spend
  is never accepted post-recovery (including after a real SIGKILL of a
  `LedgerServer` subprocess, marked slow+chaos);
* a WAL append that lands before a crash is REDOne on recovery even
  though the in-memory merge never happened;
* `RemoteNetwork` retries idempotent ops through connection drops and
  submits exactly once across a drop that races the server-side commit
  (the client consults `status()` before resubmitting);
* an injected device-plane fault during block validation degrades to
  host validation with identical verdicts;
* dispatch failures arrive typed (server exception class, not "malformed
  request"), oversized frames are rejected before allocation, and remote
  finality listeners get per-listener crash isolation.
"""
import json
import os
import random
import select
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.network import (
    BlockPolicy, Network, TxStatus, WALError, WriteAheadLog,
)
from fabric_token_sdk_tpu.services.network.remote import (
    FrameTooLarge, LedgerServer, RemoteError, RemoteNetwork, _recv_msg,
)
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    return mx.REGISTRY.counter(name).value


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def build_env(driver_factory, network):
    """issuer + alice + bob bound to `network` (in-process or remote)."""
    parties = {
        name: Party(name, driver_factory(), network)
        for name in ("issuer-node", "alice-node", "bob-node")
    }
    issuer = parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet("alice", anonymous=False)
    bob = parties["bob-node"].new_owner_wallet("bob", anonymous=False)
    validator = getattr(network, "validator", None)  # in-process only
    if validator is not None and hasattr(getattr(validator.driver, "pp", None),
                                         "add_issuer"):
        validator.driver.pp.add_issuer(issuer.identity)
    return parties, issuer, alice, bob


def fab_net(wal_path=None, policy=None, snapshot_every=0):
    pp = FabTokenPublicParams()
    net = Network(
        RequestValidator(FabTokenDriver(pp)), policy=policy,
        wal_path=wal_path, snapshot_every=snapshot_every,
    )
    return pp, net


def issue_to(parties, alice, values, anchor):
    tx = Transaction(parties["issuer-node"], anchor)
    tx.issue(
        "issuer", "USD", list(values),
        [alice.recipient_identity()] * len(values), anonymous=False,
    )
    tx.collect_endorsements(None)
    tx.submit()
    return tx


def manual_transfer(party, token_id, value, recipient, anchor):
    """Assemble + sign a transfer spending ONE specific token, bypassing
    the selector (whose locks would forbid crafting a double spend)."""
    req = party.tms.new_request(anchor)
    tokens, metas = party.vault.get_many([token_id])
    party.tms.add_transfer(req, [token_id], tokens, metas, "USD", [value], [recipient])
    party.tms.sign_transfers(req)
    return req


# ===================================================================
# WAL journal unit behavior
# ===================================================================


def test_wal_append_replay_roundtrip(tmp_path):
    wal = WriteAheadLog(tmp_path / "t.wal")
    payloads = [b"alpha", b"", b"\x00" * 1000, b"tail"]
    for p in payloads:
        wal.append(p)
    assert wal.replay() == payloads
    # replay is non-destructive for intact journals, and append continues
    wal.append(b"more")
    assert wal.replay() == payloads + [b"more"]
    wal.close()


def test_wal_torn_tail_truncated(tmp_path):
    path = tmp_path / "t.wal"
    wal = WriteAheadLog(path)
    wal.append(b"one")
    wal.append(b"two")
    before = _counter("wal.torn_tails")
    # a partial record: valid-looking header promising more than exists
    with open(path, "ab") as fh:
        fh.write(struct.pack(">II", 4096, 0xDEAD) + b"only-a-fragment")
    assert wal.replay() == [b"one", b"two"]
    assert _counter("wal.torn_tails") - before == 1
    # the tail was truncated: the journal is clean again and appendable
    wal.append(b"three")
    assert wal.replay() == [b"one", b"two", b"three"]
    assert _counter("wal.torn_tails") - before == 1
    wal.close()


def test_wal_crc_corruption_is_a_torn_tail(tmp_path):
    path = tmp_path / "t.wal"
    wal = WriteAheadLog(path)
    wal.append(b"good-record")
    wal.append(b"bad-record!")
    with open(path, "r+b") as fh:  # flip one payload byte of the LAST record
        fh.seek(-1, os.SEEK_END)
        last = fh.read(1)
        fh.seek(-1, os.SEEK_END)
        fh.write(bytes([last[0] ^ 0xFF]))
    before = _counter("wal.torn_tails")
    assert wal.replay() == [b"good-record"]
    assert _counter("wal.torn_tails") - before == 1
    wal.close()


# ===================================================================
# Ledger durability: recover from WAL + snapshot compaction
# ===================================================================


def _seed_and_pay(net, pp, n_tokens=3):
    """Seed block + (n_tokens - 1) transfer blocks, plus a correctly
    signed conflicting spend of the first token (crafted from live vault
    state BEFORE its input is consumed) for post-recovery MVCC checks."""
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), net)
    issue_to(parties, alice, [5] * n_tokens, "seed")
    alice_p = parties["alice-node"]
    ids = alice_p.vault.token_ids()
    dup = manual_transfer(alice_p, ids[0], 5, bob.recipient_identity(), "dup")
    for i, tid in enumerate(ids[: n_tokens - 1]):
        req = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"pay-{i}")
        ev = net.submit(req.to_bytes())
        assert ev.status == TxStatus.VALID
    return parties, alice, bob, ids, dup


def test_network_recover_replays_wal(tmp_path):
    wal_path = str(tmp_path / "ledger.wal")
    pp, net = fab_net(wal_path=wal_path)
    _, _, _, ids, dup = _seed_and_pay(net, pp)

    net2 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net2.height() == net.height() == 3
    for anchor in ("seed", "pay-0", "pay-1"):
        assert net2.status(anchor).status == TxStatus.VALID
    assert net2.block(1).txs == ["pay-0"]
    # state identical: spent inputs gone, outputs resolvable
    assert not net2.exists(ID("seed", 0))
    assert net2.resolve_input(ID("pay-0", 0)) == net.resolve_input(ID("pay-0", 0))
    assert net2.exists(ID("seed", 2)) and net.exists(ID("seed", 2))
    # and the recovered ledger still enforces MVCC: a correctly-signed
    # double spend of the recovered-spent seed.0 is rejected
    ev = net2.submit(dup.to_bytes())
    assert ev.status == TxStatus.INVALID
    assert "already spent" in ev.message


def test_snapshot_compaction_truncates_replayed_prefix(tmp_path):
    wal_path = str(tmp_path / "ledger.wal")
    pp = FabTokenPublicParams()
    before_snaps = _counter("wal.snapshots")
    net = Network(
        RequestValidator(FabTokenDriver(pp)), wal_path=wal_path, snapshot_every=2
    )
    _seed_and_pay(net, pp, n_tokens=4)  # 4 blocks: seed + pay-0..2
    assert _counter("wal.snapshots") - before_snaps == 2  # at heights 2, 4
    assert os.path.exists(wal_path + ".snap")
    # compaction truncated the journal: only the un-snapshotted suffix is
    # replayed (here: nothing — height 4 snapshot covers everything)
    assert WriteAheadLog(wal_path).replay() == []
    net2 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net2.height() == 4
    assert net2.status("pay-2").status == TxStatus.VALID
    # post-recovery commits keep journaling + compacting on the same files
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), net2)
    issue_to(parties, alice, [1], "post")
    assert net2.height() == 5
    net3 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net3.height() == 5 and net3.status("post").status == TxStatus.VALID


def test_crash_between_wal_append_and_merge_redoes_block(tmp_path, monkeypatch):
    """The WAL-before-merge ordering: a block whose record is fsync'd but
    whose in-memory merge crashed is REDOne on recovery. The submitter
    never saw finality — it re-learns the verdict via status()."""
    wal_path = str(tmp_path / "ledger.wal")
    pp, net = fab_net(wal_path=wal_path)
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), net)
    issue_to(parties, alice, [5], "seed")
    alice_p = parties["alice-node"]
    tid = alice_p.vault.token_ids()[0]
    req = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), "pay")

    from fabric_token_sdk_tpu.services.network import ledger as ledger_mod

    def crash(self):
        raise OSError("simulated crash between WAL append and merge")

    monkeypatch.setattr(ledger_mod._BlockView, "merge", crash)
    with pytest.raises(OSError):
        net.submit(req.to_bytes())
    assert net.status("pay") is None  # crashed node never applied it
    monkeypatch.undo()

    net2 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net2.status("pay").status == TxStatus.VALID  # redo from journal
    assert net2.exists(ID("pay", 0)) and not net2.exists(ID("seed", 0))
    # and replaying the identical submission is the idempotent no-op
    assert net2.submit(req.to_bytes()).status == TxStatus.VALID
    assert net2.height() == 2


def test_injected_wal_fault_fails_commit_without_finality(tmp_path):
    """An injected `wal.append` fault loses the block BEFORE anything was
    promised: the submitter gets an error, nothing durable is recorded,
    and an identical resubmission succeeds once the fault clears."""
    wal_path = str(tmp_path / "ledger.wal")
    pp, net = fab_net(wal_path=wal_path)
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), net)
    faults.arm("wal.append", "error", count=1)
    before = _counter("faults.injected.wal.append")
    with pytest.raises(faults.FaultInjected):
        issue_to(parties, alice, [5], "seed")
    assert _counter("faults.injected.wal.append") - before == 1
    assert net.status("seed") is None and net.height() == 0
    issue_to(parties, alice, [5], "seed")  # fault expended: succeeds
    assert net.status("seed").status == TxStatus.VALID
    net2 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net2.height() == 1 and net2.status("seed").status == TxStatus.VALID


def test_failed_wal_append_rolls_back_journal(tmp_path, monkeypatch):
    """An append that fails AFTER its bytes hit the file (fsync ENOSPC)
    must roll the journal back to the pre-append boundary — otherwise the
    aborted block's record survives, the next commit journals the same
    height again, and recovery resurrects the wrong block."""
    wal_path = str(tmp_path / "ledger.wal")
    pp, net = fab_net(wal_path=wal_path)
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), net)
    issue_to(parties, alice, [5], "seed")
    alice_p = parties["alice-node"]
    tid = alice_p.vault.token_ids()[0]
    req = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), "pay")

    from fabric_token_sdk_tpu.services.network import wal as wal_mod

    def flaky_fsync(fd):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(wal_mod.os, "fsync", flaky_fsync)
    before = _counter("wal.append_failures")
    with pytest.raises(OSError):
        net.submit(req.to_bytes())
    monkeypatch.undo()
    assert _counter("wal.append_failures") - before == 1
    assert not net._wal.poisoned  # rollback succeeded: journal is clean
    assert len(WriteAheadLog(wal_path).replay()) == 1  # the record is GONE
    # the retried commit journals at the correct height; recovery agrees
    assert net.submit(req.to_bytes()).status == TxStatus.VALID
    net2 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net2.height() == 2
    assert net2.status("pay").status == TxStatus.VALID


def test_recover_rejects_forked_journal(tmp_path):
    """Two records journaled at ONE height (the hole the append rollback
    closes) must fail recovery loudly, never resurrect a forked ledger."""
    from fabric_token_sdk_tpu.crypto.serialization import dumps

    wal_path = str(tmp_path / "forked.wal")
    wal = WriteAheadLog(wal_path)
    rec = {"height": 0, "ts": 0.0, "requests": [],
           "txs": [["a", "Valid", ""]], "consumed": [], "outputs": {}}
    wal.append(dumps(rec))
    wal.append(dumps(rec))  # second block at the SAME height
    wal.close()
    with pytest.raises(WALError):
        Network.recover(
            RequestValidator(FabTokenDriver(FabTokenPublicParams())), wal_path
        )


def test_snapshot_failure_does_not_poison_commit(tmp_path, monkeypatch):
    """Compaction runs after the block is durably journaled: a snapshot
    failure is counted and logged, but the commit acknowledgement,
    listeners, and a later recovery are untouched."""
    wal_path = str(tmp_path / "ledger.wal")
    pp = FabTokenPublicParams()
    net = Network(
        RequestValidator(FabTokenDriver(pp)), wal_path=wal_path, snapshot_every=1
    )
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), net)

    def broken_compact(self):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(Network, "_compact", broken_compact)
    before = _counter("wal.snapshot_failures")
    issue_to(parties, alice, [5], "seed")  # must commit despite the failure
    assert _counter("wal.snapshot_failures") - before == 1
    assert net.status("seed").status == TxStatus.VALID
    assert parties["alice-node"].balance("USD") == 5  # listeners ran
    monkeypatch.undo()
    net2 = Network.recover(RequestValidator(FabTokenDriver(pp)), wal_path)
    assert net2.height() == 1
    assert net2.status("seed").status == TxStatus.VALID


# ===================================================================
# Fault-injection framework
# ===================================================================


def test_faults_env_parse_count_and_kinds():
    before = _counter("faults.injected.t.site")
    assert faults.load_env("t.site:error:1.0:2") == 1
    with pytest.raises(faults.FaultInjected):
        faults.fire("t.site")
    with pytest.raises(faults.FaultInjected):
        faults.fire("t.site")
    faults.fire("t.site")  # count expended: no-op
    assert _counter("faults.injected.t.site") - before == 2

    faults.arm("t.drop", "drop")
    with pytest.raises(ConnectionError):  # drop is transport-shaped
        faults.fire("t.drop")
    faults.arm("t.delay", "delay", delay_s=0.05)
    t0 = time.monotonic()
    faults.fire("t.delay")
    assert time.monotonic() - t0 >= 0.04
    faults.arm("t.never", "error", prob=0.0)
    faults.fire("t.never")  # prob 0 never fires
    assert "t.delay" in faults.armed()
    faults.clear()
    assert faults.armed() == {}
    faults.fire("t.site")  # disarmed: plain no-op
    # empty optional fields keep their defaults (unlimited count here)
    assert faults.load_env("t.skip:delay:1.0::0.05") == 1
    assert faults.armed()["t.skip"] == "delay"
    t0 = time.monotonic()
    faults.fire("t.skip")
    faults.fire("t.skip")  # count '' = unlimited: still armed
    assert time.monotonic() - t0 >= 0.08
    faults.clear()
    with pytest.raises(ValueError):
        faults.load_env("missing-kind")
    with pytest.raises(ValueError):
        faults.arm("x", "explode")


# ===================================================================
# Remote path under faults
# ===================================================================


def _remote_env(policy=None, wal_path=None):
    pp = FabTokenPublicParams()
    server = LedgerServer(
        RequestValidator(FabTokenDriver(pp)), policy=policy, wal_path=wal_path
    ).start()
    client = RemoteNetwork(server.address, timeout=10, backoff_s=0.01)
    return pp, server, client


def test_remote_retry_through_connection_drops():
    pp, server, client = _remote_env()
    try:
        faults.arm("remote.send", "drop", count=2)
        before = _counter("remote.retry.attempts")
        assert client.height() == 0  # succeeds through 2 dropped attempts
        assert _counter("remote.retry.attempts") - before == 2
        # exhausted retries surface as a clean ConnectionError
        faults.arm("remote.send", "drop")  # unlimited
        ex_before = _counter("remote.retry.exhausted")
        with pytest.raises(ConnectionError):
            client.height()
        assert _counter("remote.retry.exhausted") - ex_before == 1
    finally:
        faults.clear()
        server.stop()


def _one_issue(pp, client, anchor, value=9):
    parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), client)
    tx = Transaction(parties["issuer-node"], anchor)
    tx.issue("issuer", "USD", [value], [alice.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(None)
    return parties, tx


def test_remote_submit_exactly_once_across_recv_drop():
    """Acceptance: the connection drops after the server commits but
    before the client reads the response; the client recovers the verdict
    via status() and the tx commits EXACTLY once — block count and vault
    balance agree with a no-fault run."""
    # no-fault run: the expected deltas
    pp0, server0, client0 = _remote_env()
    try:
        blocks_before = _counter("ledger.blocks.committed")
        parties0, tx0 = _one_issue(pp0, client0, "mint")
        ev = client0.submit(tx0.request.to_bytes())
        assert ev.status == TxStatus.VALID
        expected_blocks = _counter("ledger.blocks.committed") - blocks_before
        expected_balance = parties0["alice-node"].balance("USD")
    finally:
        server0.stop()
    assert expected_blocks == 1 and expected_balance == 9

    # fault run: FTS_FAULTS drops the client connection on the response.
    # The wider backoff gives the server-side commit (already in flight
    # when the drop fires) time to finish before the status consult.
    pp1 = FabTokenPublicParams()
    server1 = LedgerServer(RequestValidator(FabTokenDriver(pp1))).start()
    client1 = RemoteNetwork(server1.address, timeout=10, backoff_s=0.1)
    try:
        parties1, tx1 = _one_issue(pp1, client1, "mint")
        blocks_before = _counter("ledger.blocks.committed")
        recovered_before = _counter("remote.submit.recovered")
        assert faults.load_env("remote.recv:drop:1.0:1") == 1
        ev = client1.submit(tx1.request.to_bytes())
        assert ev.status == TxStatus.VALID and ev.tx_id == "mint"
        # exactly once: same block delta, same balance as the no-fault run
        assert _counter("ledger.blocks.committed") - blocks_before == expected_blocks
        assert parties1["alice-node"].balance("USD") == expected_balance
        assert _counter("remote.submit.recovered") - recovered_before == 1
        assert client1.status("mint").status == TxStatus.VALID
    finally:
        faults.clear()
        server1.stop()


def test_remote_dispatch_errors_are_typed():
    pp, server, client = _remote_env()
    try:
        before = _counter("remote.dispatch.errors.resolve")
        with pytest.raises(RemoteError) as ei:
            client._call({"op": "resolve", "tx_id": "x"})  # missing "index"
        assert ei.value.error_class == "KeyError"
        assert "index" in str(ei.value)
        assert _counter("remote.dispatch.errors.resolve") - before == 1
        # unknown op is typed too, and the connection survives both
        with pytest.raises(RemoteError) as ei:
            client._call({"op": "frobnicate"})
        assert ei.value.error_class == "UnknownOp"
        assert client.height() == 0
    finally:
        server.stop()


def test_remote_frame_cap_client_and_server():
    # client side: a hostile length prefix is rejected before allocation
    a, b = socket.socketpair()
    try:
        a.sendall((99 * 1024 * 1024).to_bytes(4, "big"))
        with pytest.raises(FrameTooLarge):
            _recv_msg(b)
    finally:
        a.close()
        b.close()

    # server side: typed error response, then the connection is dropped
    pp, server, client = _remote_env()
    try:
        before = _counter("remote.frames.rejected")
        s = socket.create_connection(server.address, timeout=10)
        s.sendall((64 * 1024 * 1024).to_bytes(4, "big") + b"xx")
        resp = _recv_msg(s)
        assert resp == {
            "ok": False,
            "error": "frame of 67108864 bytes exceeds cap of 16777216",
            "error_class": "FrameTooLarge",
        }
        assert s.recv(1) == b""  # server closed the desynced stream
        s.close()
        assert _counter("remote.frames.rejected") - before == 1
        assert client.height() == 0  # server loop unharmed
    finally:
        server.stop()


def test_remote_listener_crash_isolation():
    pp, server, client = _remote_env()
    try:
        seen = []

        def boom(event, request):
            raise RuntimeError("listener crashed")

        client.subscribe(boom)
        client.subscribe(lambda e, r: seen.append(e.tx_id))
        before = _counter("remote.listener.errors")
        parties, tx = _one_issue(pp, client, "mint")
        ev = client.submit(tx.request.to_bytes())
        assert ev.status == TxStatus.VALID
        assert _counter("remote.listener.errors") - before == 1
        assert "mint" in seen  # listeners AFTER the crasher still ran
        # apply_finality mirrors the same isolation
        assert client.apply_finality(tx.request.to_bytes()).status == TxStatus.VALID
        assert _counter("remote.listener.errors") - before == 2
    finally:
        server.stop()


def test_remote_snapshot_restore_server_restart():
    """Satellite: stop a LedgerServer, restore its Network from the
    snapshot on the SAME port — the pooled client reconnects by itself
    and sees identical height/status/exists answers."""
    pp, server, client = _remote_env()
    port = server.address[1]
    try:
        parties, tx = _one_issue(pp, client, "mint", value=7)
        client.submit(tx.request.to_bytes())
        height = client.height()
        assert height == 1 and client.exists(ID("mint", 0))

        snap = server.network.snapshot()
        server.stop()
        server = LedgerServer(
            network=Network.restore(RequestValidator(FabTokenDriver(pp)), snap),
            port=port,
        ).start()
        # same client instance: its pooled socket is dead, the retry path
        # re-dials transparently
        connects_before = _counter("remote.connects")
        assert client.height() == height
        assert _counter("remote.connects") - connects_before >= 1
        assert client.status("mint").status == TxStatus.VALID
        assert client.exists(ID("mint", 0))
        assert client.resolve_input(ID("mint", 0)) == server.network.resolve_input(
            ID("mint", 0)
        )
    finally:
        server.stop()


# ===================================================================
# Device-plane fault during block validation: degrade, don't diverge
# ===================================================================


def test_batch_verify_fault_degrades_to_host_same_verdicts(zk_pp):
    """An injected `batch.verify` fault mid-block falls back to host
    validation with IDENTICAL verdicts (batching may only accelerate,
    never change, accept/reject)."""

    def run(inject):
        net = Network(
            RequestValidator(ZKATDLogDriver(zk_pp)),
            policy=BlockPolicy(max_block_txs=8, min_batch=2),
        )
        parties, issuer, alice, bob = build_env(lambda: ZKATDLogDriver(zk_pp), net)
        issue_to(parties, alice, [5, 5], "seed")
        alice_p = parties["alice-node"]
        reqs = [
            manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"pay-{i}")
            for i, tid in enumerate(alice_p.vault.token_ids())
        ]
        if inject:
            faults.arm("batch.verify", "error", count=1)
        try:
            events = net.submit_many([r.to_bytes() for r in reqs])
        finally:
            faults.clear()
        return [e.status for e in events], parties["bob-node"].balance("USD")

    host_before = _counter("ledger.validate.host")
    errors_before = _counter("ledger.block.batch_errors")
    injected = run(inject=True)
    assert _counter("ledger.block.batch_errors") - errors_before == 1
    assert _counter("ledger.validate.host") - host_before == 2  # host fallback
    clean = run(inject=False)
    assert injected == clean == ([TxStatus.VALID, TxStatus.VALID], 10)


# ===================================================================
# The real thing: SIGKILL a ledger node mid-workload, recover from WAL
# ===================================================================

_SERVER_CHILD = """
import os, sys, threading
sys.path.insert(0, sys.argv[3])
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.services.network.ledger import Network
from fabric_token_sdk_tpu.services.network.remote import LedgerServer

wal_path, mode = sys.argv[1], sys.argv[2]
validator = RequestValidator(FabTokenDriver(FabTokenPublicParams()))
if mode == "recover":
    net = Network.recover(validator, wal_path)
else:
    net = Network(validator, wal_path=wal_path)
server = LedgerServer(network=net).start()
print("READY", server.address[1], flush=True)
threading.Event().wait()
"""


def _spawn_server(wal_path, mode):
    proc = subprocess.Popen(
        [sys.executable, "-c", _SERVER_CHILD, str(wal_path), mode, REPO_ROOT],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"ledger child died rc={proc.returncode}:\n{proc.stderr.read()}"
            )
        ready, _, _ = select.select([proc.stdout], [], [], 0.2)
        if ready:
            line = proc.stdout.readline()
            assert line.startswith("READY"), f"unexpected child output {line!r}"
            return proc, int(line.split()[1])
    proc.kill()
    raise AssertionError("ledger child never became ready")


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_ledger_server_recovers_from_wal(tmp_path):
    """Acceptance: SIGKILL a LedgerServer subprocess mid-workload;
    restart it via Network.recover. Every tx the client saw finality for
    is still VALID, a double spend of a recovered-spent token is
    rejected, and the (artificially) torn final WAL record is discarded
    cleanly."""
    wal_path = str(tmp_path / "node.wal")
    child, port = _spawn_server(wal_path, "fresh")
    child2 = None
    try:
        client = RemoteNetwork(("127.0.0.1", port), timeout=10,
                               retries=2, backoff_s=0.01)
        pp = FabTokenPublicParams()
        parties, issuer, alice, bob = build_env(lambda: FabTokenDriver(pp), client)
        issue_to(parties, alice, [2] * 6, "seed")
        alice_p = parties["alice-node"]
        ids = alice_p.vault.token_ids()
        reqs = [
            manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"t-{i}")
            for i, tid in enumerate(ids)
        ]
        # a conflicting spend of t-0's input, prepared BEFORE the kill
        dup = manual_transfer(alice_p, ids[0], 2, bob.recipient_identity(), "dup")

        acked = ["seed"]
        for i in range(3):  # definitely-acknowledged prefix
            ev = client.submit(reqs[i].to_bytes())
            assert ev.status == TxStatus.VALID
            acked.append(f"t-{i}")

        # mid-workload kill: t-3/t-4 race SIGKILL from a second thread
        def straggler():
            for i in (3, 4):
                try:
                    ev = client.submit(reqs[i].to_bytes())
                    if ev.status == TxStatus.VALID:
                        acked.append(f"t-{i}")
                except (ConnectionError, OSError):
                    return

        t = threading.Thread(target=straggler)
        t.start()
        time.sleep(0.02)
        os.kill(child.pid, signal.SIGKILL)
        t.join(timeout=30)
        assert not t.is_alive()
        child.wait(timeout=30)

        # torn tail: simulate a crash mid-append of the NEXT record
        assert os.path.getsize(wal_path) > 0
        with open(wal_path, "ab") as fh:
            fh.write(struct.pack(">II", 1 << 20, 0) + b"torn")

        child2, port2 = _spawn_server(wal_path, "recover")
        client2 = RemoteNetwork(("127.0.0.1", port2), timeout=10,
                                retries=2, backoff_s=0.01)
        # every acknowledged tx survived the SIGKILL
        assert client2.height() >= len(acked)
        for anchor in acked:
            assert client2.status(anchor).status == TxStatus.VALID, anchor
        for anchor in acked:
            if anchor == "seed":
                continue
            i = int(anchor.split("-")[1])
            assert client2.exists(ID(anchor, 0))
            assert not client2.exists(ID("seed", i))
        # the in-flight stragglers either committed (and are consistent)
        # or were lost before the WAL append — never half-applied
        for i in (3, 4):
            ev = client2.status(f"t-{i}")
            assert ev is None or ev.status == TxStatus.VALID
            if ev is not None:
                assert not client2.exists(ID("seed", i))
        # no double spend accepted post-recovery
        ev = client2.submit(dup.to_bytes())
        assert ev.status == TxStatus.INVALID
        assert "already spent" in ev.message
        # and the recovered node accepts genuinely new work
        ev = client2.submit(reqs[5].to_bytes())
        assert ev.status == TxStatus.VALID
    finally:
        for c in (child, child2):
            if c is not None and c.poll() is None:
                c.kill()


# ===================================================================
# Hung-device chaos: bounded dispatch + breaker keep a node live
# ===================================================================

_HANG_CHILD = """
import json, os, random, sys, time
sys.path.insert(0, sys.argv[1])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["FTS_BREAKER_TIMEOUTS"] = "1"     # one timeout opens the plane
# wide enough that host re-validation of the hung block + client-side
# proving of the next block land INSIDE the cooldown on a loaded 2-core
# host — the "rej" block must hit an OPEN breaker, not become the probe
os.environ["FTS_BREAKER_COOLDOWN_S"] = "20.0"
from fabric_token_sdk_tpu.api.request import (
    IssueRecord, TokenRequest, TransferRecord,
)
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto import sign as csign
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers import identity
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network
from fabric_token_sdk_tpu.utils import faults, resilience
from fabric_token_sdk_tpu.utils import metrics as mx

rng = random.Random(0xF75)
pp = setup(base=4, exponent=2, rng=rng)
drv = ZKATDLogDriver(pp)
net = Network(
    RequestValidator(ZKATDLogDriver(pp)),
    policy=BlockPolicy(max_block_txs=8, min_batch=2),
)
key = csign.keygen(rng)
ident = identity.pk_identity(key.public)

out = drv.issue(ident, "USD", [7, 7], [ident, ident], anonymous=False)
req = TokenRequest(anchor="seed")
req.issues.append(IssueRecord(action=out.action_bytes, issuer=ident,
                              outputs_metadata=out.metadata,
                              receivers=[ident, ident]))
req.issues[0].signature = key.sign(req.marshal_to_sign(), rng)
ev = net.submit(req.to_bytes())
assert ev.status.value == "Valid", ev.message
chains = [
    (ID("seed", i), out.outputs[i], out.metadata[i]) for i in range(2)
]

def block(tag):
    # one block of 2 same-shape (1,1) transfers -> ONE device group call
    global chains
    batch, nxt = [], []
    for i, (tid, raw, meta) in enumerate(chains):
        t = drv.transfer([tid], [raw], [meta], "USD", [7], [ident])
        tr = TokenRequest(anchor=f"{tag}-{i}")
        tr.transfers.append(TransferRecord(
            action=t.action_bytes, input_ids=[tid], senders=[ident],
            outputs_metadata=t.metadata, receivers=[ident]))
        tr.transfers[0].signatures = [key.sign(tr.marshal_to_sign(), rng)]
        batch.append(tr.to_bytes())
        nxt.append((ID(f"{tag}-{i}", 0), t.outputs[0], t.metadata[0]))
    t0 = time.monotonic()
    events = net.submit_many(batch)
    wall = time.monotonic() - t0
    assert all(e.status.value == "Valid" for e in events), [
        e.message for e in events
    ]
    chains = nxt
    return wall

def ctr(name):
    return mx.REGISTRY.counter(name).value

# round 0 (unbounded): pay the compile, prove the device path works
warm_wall = block("warm")
batched_warm = ctr("ledger.validate.batched")
assert batched_warm >= 2, "warmup block did not ride the device plane"

# rounds 1..3 under a 2s deadline: hang -> host fallback + breaker opens
os.environ["FTS_DEVICE_DEADLINE_VERIFY_S"] = "2"
faults.arm("batch.verify", "hang", count=1, delay_s=600)
hang_wall = block("hung")
faults.disarm("batch.verify")  # release the abandoned worker
open_n = ctr("resilience.breaker.open")
state_after_hang = resilience.breaker_states().get("verify")
# only assert the instant-rejection behavior when the breaker is STILL
# open as the block dispatches — on a badly loaded host the preceding
# zk work can outlast even the 20s cooldown, making this block the
# half-open probe instead (correct product behavior, different branch)
rej_applicable = resilience.breaker_states().get("verify") == "open"
rejected_wall = block("rej")   # inside cooldown: instant host fallback
rejected_n = ctr("resilience.breaker.rejected")
# the emulated CPU device plane legitimately needs more than 2s per
# verify — relax the (per-dispatch, env-read) deadline so the half-open
# probe is judged on health, not on emulation speed
os.environ["FTS_DEVICE_DEADLINE_VERIFY_S"] = "300"
time.sleep(20.5)               # cooldown expires -> half-open probe
batched_before = ctr("ledger.validate.batched")
probe_wall = block("heal")
batched_after = ctr("ledger.validate.batched")
print(json.dumps({
    "ok": True,
    "warm_wall": warm_wall,
    "hang_wall": hang_wall,
    "rejected_wall": rejected_wall,
    "rej_applicable": rej_applicable,
    "probe_wall": probe_wall,
    "breaker_open": open_n,
    "state_after_hang": state_after_hang,
    "rejected": rejected_n,
    "breaker_close": ctr("resilience.breaker.close"),
    "timeouts": ctr("resilience.bounded.timeouts"),
    "reengaged_rows": batched_after - batched_before,
    "commit_p99_s": mx.REGISTRY.histogram(
        "ledger.block.commit.seconds").quantile(0.99),
}), flush=True)
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_hung_device_plane_stays_live_and_heals():
    """Acceptance (hung-device chaos): inject a `hang` at `batch.verify`
    mid-soak in a subprocess node. The block commits via host fallback
    within FTS_DEVICE_DEADLINE_S + slack (never the 600s hang cap), the
    `verify` breaker OPENS (one consecutive timeout), the next block is
    rejected up front (instant host fallback), and after the fault
    disarms + cooldown a half-open probe RE-ENGAGES the device plane —
    commit p99 stays bounded throughout."""
    proc = subprocess.run(
        [sys.executable, "-c", _HANG_CHILD, REPO_ROOT],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=840, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, f"chaos child failed:\n{proc.stderr[-4000:]}"
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"]
    # bounded: the hung block resolved near the 2s deadline, nowhere
    # near the 600s hang cap (generous slack for host zk re-validation)
    assert report["hang_wall"] < 60, report
    assert report["timeouts"] >= 1
    assert report["breaker_open"] >= 1
    assert report["state_after_hang"] == "open"
    # open breaker = instant rejection, no deadline paid on that block
    # (asserted only when the child saw the breaker still open at that
    # dispatch — else the block legitimately became the probe)
    if report["rej_applicable"]:
        assert report["rejected"] >= 1
    assert report["rejected_wall"] < 60, report
    # the plane healed: probe succeeded, device verdicts flowed again
    assert report["breaker_close"] >= 1
    assert report["reengaged_rows"] >= 2, report
    # and the node's overall commit p99 stayed bounded
    assert report["commit_p99_s"] is None or report["commit_p99_s"] < 120
