"""Distributed tracing plane: trace contexts, the crash flight recorder,
cross-process propagation over a real socket (including retry-after-drop
child spans), fault/trace correlation, and the `ftstrace` assembly CLI.

Acceptance: an 8-tx zkatdlog block submitted through `submit_many` over
`RemoteNetwork` yields one stitched trace per tx spanning client submit
-> server orderer -> batched device verify -> WAL append -> finality,
with a per-block critical-path breakdown.
"""

import json
import os
import random
import sys
import threading

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network, TxStatus
from fabric_token_sdk_tpu.services.network.remote import LedgerServer, RemoteNetwork
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture
def enabled():
    was = mx.enabled()
    mx.enable(True)
    try:
        yield
    finally:
        mx.enable(was)


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def _ftstrace():
    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftstrace
    finally:
        sys.path.pop(0)
    return ftstrace


def _trace_spans(trace_id):
    """Every recorded span (root or child) carrying `trace_id`."""
    out = []

    def walk(d):
        if d.get("trace_id") == trace_id:
            out.append(d)
        for c in d.get("children", ()):
            walk(c)

    for root in mx.REGISTRY.snapshot()["spans"]:
        walk(root)
    return out


# ------------------------------------------------------------ trace context


def test_spans_inherit_active_trace(enabled):
    ctx = mx.new_trace()
    with mx.use_trace(ctx):
        with mx.span("tr.outer") as outer:
            with mx.span("tr.inner") as inner:
                pass
    assert outer.trace_id == ctx.trace_id
    assert outer.parent_span_id == ctx.span_id
    assert inner.trace_id == ctx.trace_id
    assert inner.parent_span_id == outer.span_id
    assert outer.span_id and outer.span_id != inner.span_id
    d = outer.to_dict()
    assert d["trace_id"] == ctx.trace_id
    assert d["span_id"] == outer.span_id
    assert d["start_unix"] > 0


def test_explicit_trace_overrides_foreign_parent_span(enabled):
    """The group-commit pattern: a thread with its OWN trace open
    validates another tx under that tx's context — the explicit
    `use_trace` must win over parent-span inheritance."""
    mine, theirs = mx.new_trace(), mx.new_trace()
    with mx.use_trace(mine):
        with mx.span("tr.commit_loop") as outer:
            assert mx.current_trace().trace_id == mine.trace_id
            with mx.use_trace(theirs):
                assert mx.current_trace().trace_id == theirs.trace_id
                with mx.span("tr.validate_other") as child:
                    pass
            # restored after the override
            assert mx.current_trace().trace_id == mine.trace_id
    assert outer.trace_id == mine.trace_id
    assert child.trace_id == theirs.trace_id
    assert child.parent_span_id == theirs.span_id


def test_trace_context_wire_round_trip():
    ctx = mx.new_trace()
    assert mx.TraceContext.from_wire(ctx.to_wire()) == ctx
    assert mx.TraceContext.from_wire(None) is None
    assert mx.TraceContext.from_wire([]) is None
    assert mx.TraceContext.from_wire(["t-only"]) == mx.TraceContext("t-only", "")


def test_record_span_lands_in_registry(enabled):
    ctx = mx.new_trace()
    s = mx.record_span("tr.manual", 100.0, 101.5, trace=ctx, tx="m1")
    assert s.duration == pytest.approx(1.5)
    assert s.trace_id == ctx.trace_id
    found = [d for d in _trace_spans(ctx.trace_id) if d["name"] == "tr.manual"]
    assert found and found[0]["attrs"]["tx"] == "m1"


# ------------------------------------------------------------ flight recorder


def test_flight_ring_bounded_under_sustained_load():
    """Concurrent writers can never grow the ring past capacity; the
    newest events survive, the oldest are evicted."""
    fr = mx.FlightRecorder(capacity=64)
    threads = [
        threading.Thread(
            target=lambda k: [fr.record("tick", worker=k, i=i) for i in range(500)],
            args=(k,),
        )
        for k in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = fr.tail()
    assert len(events) == 64
    assert len(fr) == 64
    # the ring holds the TAIL of the stream: every surviving event is
    # from the back half of some worker's sequence
    assert all(e["i"] >= 500 - 64 for e in events)
    fr.record("last", i=-1)
    assert fr.tail(1)[0]["kind"] == "last"
    assert len(fr) == 64


def test_flight_event_tagged_with_active_trace():
    ctx = mx.new_trace()
    with mx.use_trace(ctx):
        mx.flight("tr.tagged", detail=1)
    evt = mx.FLIGHT.tail(1)[0]
    assert evt["kind"] == "tr.tagged"
    assert evt["trace_id"] == ctx.trace_id


def test_fault_firing_correlates_to_trace():
    """Satellite: an injected fault's flight event carries the trace id
    of the tx it hit."""
    ctx = mx.new_trace()
    faults.arm("tr.fault_site", "error", count=1)
    with mx.use_trace(ctx):
        with pytest.raises(faults.FaultInjected):
            faults.fire("tr.fault_site")
    evt = [e for e in mx.FLIGHT.tail() if e["kind"] == "fault"][-1]
    assert evt["site"] == "tr.fault_site"
    assert evt["trace_id"] == ctx.trace_id


def test_flight_dump_and_ftstrace_tail(tmp_path, capsys):
    fr = mx.FlightRecorder(capacity=16)
    for i in range(5):
        fr.record("dump.check", i=i)
    path = str(tmp_path / "t.flight.json")
    assert fr.dump(path) == path
    doc = json.loads(open(path).read())
    assert doc["capacity"] == 16
    assert [e["i"] for e in doc["events"]] == list(range(5))
    assert doc["pid"] == os.getpid()
    ftstrace = _ftstrace()
    assert ftstrace.tail(path, n=3) == 0
    out = capsys.readouterr().out
    assert "dump.check" in out and "i=4" in out
    # -n bounds the rows: i=0 rolled out of the view
    assert "i=0" not in out


def test_flush_sidecar_also_dumps_flight(tmp_path):
    mx.flight("sidecar.check")
    p = str(tmp_path / "x.metrics.json")
    assert mx.flush_sidecar(p) == p
    flight = str(tmp_path / "x.flight.json")
    assert os.path.exists(flight)
    doc = json.loads(open(flight).read())
    assert any(e["kind"] == "sidecar.check" for e in doc["events"])


# ------------------------------------------------------------ remote propagation


def _fab_remote_env(tmp_path=None, **client_kw):
    pp = FabTokenPublicParams()
    wal = str(tmp_path / "ledger.wal") if tmp_path is not None else None
    net = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(max_block_txs=16, min_batch=1),
        wal_path=wal,
    )
    server = LedgerServer(network=net).start()
    client = RemoteNetwork(server.address, **client_kw)
    issuer_p = Party("issuer", FabTokenDriver(pp), client)
    alice_p = Party("alice", FabTokenDriver(pp), client)
    bob_p = Party("bob", FabTokenDriver(pp), client)
    iw = issuer_p.new_issuer_wallet("issuer")
    pp.add_issuer(iw.identity)
    alice = alice_p.new_owner_wallet("alice", anonymous=False)
    bob = bob_p.new_owner_wallet("bob", anonymous=False)
    return server, client, issuer_p, alice_p, bob_p, alice, bob


def test_remote_trace_propagation_with_retry_after_drop(enabled):
    """Satellite acceptance: client span + server span share ONE trace id
    across a real socket, and the retry after a dropped connection shows
    up as a child span in the same trace."""
    server, client, issuer_p, alice_p, bob_p, alice, bob = _fab_remote_env(
        retries=3, backoff_s=0.001
    )
    try:
        tx = Transaction(issuer_p, "tr-mint")
        tx.issue("issuer", "USD", [9], [alice.recipient_identity()],
                 anonymous=False)
        tx.collect_endorsements(None)
        # drop the client connection exactly once, mid-submit (after the
        # request frame went out, before the response is read)
        faults.arm("remote.recv", "drop", count=1)
        event = tx.submit()
    finally:
        faults.clear()
        server.stop()
    assert event.status == TxStatus.VALID
    assert event.trace_id == tx.trace.trace_id

    spans = _trace_spans(tx.trace.trace_id)
    names = [s["name"] for s in spans]
    # client-side legs
    assert "remote.submit" in names
    # server-side legs, SAME trace id — propagated through the frame
    assert "remote.server.dispatch" in names
    assert "network.validate" in names
    assert "orderer.queue" in names
    # the drop is visible as retry work inside the trace: either a second
    # wire attempt or a status-recovery probe (commit raced the drop)
    attempts = [s for s in names if s in ("remote.submit.attempt",
                                          "remote.submit.recover")]
    assert len(attempts) >= 2, names
    # the injected fault itself is flight-recorded WITH the trace id
    fault_evts = [
        e for e in mx.FLIGHT.tail()
        if e["kind"] == "fault" and e.get("site") == "remote.recv"
    ]
    assert fault_evts and fault_evts[-1]["trace_id"] == tx.trace.trace_id


# ------------------------------------------------------------ acceptance


def test_8tx_zk_block_stitched_traces_over_remote(zk_pp, tmp_path, capsys,
                                                  enabled):
    """ISSUE acceptance: 8 same-shape zkatdlog transfers through
    `RemoteNetwork.submit_many` ride ONE batched device verify inside one
    block, and `ftstrace` assembles one stitched per-tx trace covering
    client submit -> server orderer -> batched device verify -> WAL
    append -> finality, plus the per-block critical-path breakdown."""
    pp = zk_pp
    wal_path = str(tmp_path / "zk-ledger.wal")
    net = Network(
        RequestValidator(ZKATDLogDriver(pp)),
        policy=BlockPolicy(max_block_txs=16, min_batch=2),
        wal_path=wal_path,
    )
    server = LedgerServer(network=net).start()
    # generous socket timeout: the first batched verify in a process pays
    # one-time stage-tile loads from the persistent cache (minutes on a
    # small cold host), all inside ONE submit_many round trip
    client = RemoteNetwork(server.address, timeout=600)
    issuer_p = Party("issuer", ZKATDLogDriver(pp), client)
    alice_p = Party("alice", ZKATDLogDriver(pp), client)
    bob_p = Party("bob", ZKATDLogDriver(pp), client)
    iw = issuer_p.new_issuer_wallet("issuer")
    pp.add_issuer(iw.identity)
    alice = alice_p.new_owner_wallet("alice", anonymous=False)
    bob = bob_p.new_owner_wallet("bob", anonymous=False)
    try:
        seed = Transaction(issuer_p, "zk-seed")
        seed.issue("issuer", "USD", [5] * 8,
                   [alice.recipient_identity()] * 8, anonymous=False)
        seed.collect_endorsements(None)
        seed.submit()

        reqs = []
        for i in range(8):
            t = Transaction(alice_p, f"zk-pay-{i}")
            t.transfer("alice", "USD", [5], [bob.recipient_identity()])  # (1,1)
            t.collect_endorsements(None)
            reqs.append(t.request.to_bytes())

        batched_before = mx.REGISTRY.counter("ledger.validate.batched").value
        bt_before = mx.REGISTRY.counter("batch.transfer.txs").value
        h0 = net.height()
        events = client.submit_many(reqs)
    finally:
        server.stop()

    assert [e.status for e in events] == [TxStatus.VALID] * 8
    # one block, all 8 proofs through the batched device plane
    assert net.height() == h0 + 1
    assert mx.REGISTRY.counter("ledger.validate.batched").value - batched_before == 8
    assert mx.REGISTRY.counter("batch.transfer.txs").value - bt_before == 8
    # one DISTINCT trace per tx, reported on the finality event
    trace_ids = [e.trace_id for e in events]
    assert all(trace_ids) and len(set(trace_ids)) == 8

    # per-tx stitched trace: client leg + server orderer leg + validate
    for event in events:
        names = {s["name"] for s in _trace_spans(event.trace_id)}
        assert "remote.submit" in names, (event.tx_id, names)
        assert "orderer.queue" in names, (event.tx_id, names)
        assert "network.validate" in names, (event.tx_id, names)

    # the block's critical path covers all 8 traces, with the device
    # verify and WAL legs broken out
    commits = [
        e for e in mx.FLIGHT.tail()
        if e["kind"] == "block.commit" and set(trace_ids) <= set(e.get("traces", ()))
    ]
    assert len(commits) == 1
    commit = commits[0]
    assert commit["txs"] == [f"zk-pay-{i}" for i in range(8)]
    assert commit["device_verify_s"] > 0
    assert commit["wal_s"] > 0
    wal_evts = [
        e for e in mx.FLIGHT.tail()
        if e["kind"] == "wal.append" and e.get("txs") == commit["txs"]
    ]
    assert wal_evts, "no wal.append flight event for the block"
    dev_evts = [
        e for e in mx.FLIGHT.tail()
        if e["kind"] == "verify.device" and e.get("txs") == 8
    ]
    assert dev_evts and dev_evts[-1]["ok"] == 8

    # ftstrace assembles the timeline from dumped sidecars (client and
    # server share this process; the stitching logic is file-agnostic)
    metrics_path = str(tmp_path / "run.metrics.json")
    assert mx.flush_sidecar(metrics_path) == metrics_path
    flight_path = str(tmp_path / "run.flight.json")
    assert os.path.exists(flight_path)
    ftstrace = _ftstrace()
    rc = ftstrace.timeline("zk-pay-3", [metrics_path, flight_path])
    out = capsys.readouterr().out
    assert rc == 0
    for needle in ("remote.submit", "orderer.queue", "network.validate",
                   "critical path", "device_verify", "wal", "finality"):
        assert needle in out, f"timeline missing {needle}:\n{out}"

    # Chrome-trace export parses and carries span + flight events
    chrome_path = str(tmp_path / "chrome.json")
    assert ftstrace.export(chrome_path, [metrics_path, flight_path]) == 0
    capsys.readouterr()
    doc = json.loads(open(chrome_path).read())
    phases = {e["ph"] for e in doc["traceEvents"]}
    assert {"X", "i", "M"} <= phases
    names = {e["name"] for e in doc["traceEvents"]}
    assert "network.validate" in names and "block.commit" in names
