"""Bench-result schema: the recorded rounds (`BENCH_r*.json`) and every
freshly emitted result (full AND degraded) must validate against ONE
shared helper (`utils/benchschema.py`) — the same helper `ftstop
compare` uses — so no future bench round ever lands unparseable."""

import glob
import json
import os

import bench
from fabric_token_sdk_tpu.utils import benchschema

REPO = os.path.join(os.path.dirname(__file__), "..")


def test_recorded_bench_rounds_validate():
    """Every committed round with a parsed result (main run AND the
    default_run rider) passes the schema."""
    rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    assert rounds, "no recorded bench rounds found"
    checked = 0
    for path in rounds:
        with open(path) as fh:
            doc = json.load(fh)
        for sub in (doc, doc.get("default_run") or {}):
            result = benchschema.extract_result(sub)
            if result is None:
                continue  # parsed: null rounds predate the schema
            problems = benchschema.validate_result(result)
            assert not problems, f"{path}: {problems}"
            checked += 1
    assert checked >= 2, "BENCH_r09.json should contribute two results"


def test_fresh_full_result_validates():
    r = bench.headline_result(
        rate=12.5, platform="cpu", batch=8, runs=2, warm_s=3.0,
        provegen_s=10.0, provegen_host_s=0.4, prove_txs=4, prove_rate=0.4,
        host_rate=5.0, prove_degraded=False, setup_s=0.1, stage_warmup_s=60.0,
    )
    assert benchschema.validate_result(r) == []
    assert not benchschema.is_degraded(r)
    # the enriched block-phase superset still validates
    r.update({"block_txs_per_s": 0.05, "block_vs_baseline": 0.0,
              "block_txs": 8, "block_batched_frac": 1.0,
              "block_provegen_s": 2.0, "wal_overhead_frac": 0.001})
    assert benchschema.validate_result(r) == []
    # host_rate == 0 makes prove_vs_host null — still schema-valid
    r2 = bench.headline_result(
        rate=1.0, platform="cpu", batch=1, runs=1, warm_s=0.0,
        provegen_s=0.0, provegen_host_s=0.0, prove_txs=1, prove_rate=0.0,
        host_rate=0.0, prove_degraded=True, setup_s=0.0, stage_warmup_s=0.0,
    )
    assert r2["prove_vs_host"] is None
    assert benchschema.validate_result(r2) == []


def test_fresh_degraded_result_validates():
    snap = {
        "gauges": {"bench.throughput_tx_per_s": 0.0,
                   "bench.stage_warmup_s": 291.7,
                   "bench.prove_txs_per_s": 0.013},
        "meta": {"progress.phase": "warmup_compile"},
    }
    r = bench.degraded_result("cpu", 2000.0, snap)
    assert benchschema.is_degraded(r)
    assert benchschema.validate_result(r) == []
    assert r["phase"] == "warmup_compile"
    assert r["prove_txs_per_s"] == 0.013
    # empty registry (deadline fired before any gauge existed)
    r0 = bench.degraded_result("cpu", 8.0, {})
    assert benchschema.validate_result(r0) == []
    assert r0["prove_txs_per_s"] is None  # nullable, still valid


def test_schema_rejects_malformed_results():
    assert benchschema.validate_result(None)
    assert benchschema.validate_result([1, 2])
    r = bench.degraded_result("cpu", 8.0, {})
    for key, bad in (("metric", "other"), ("unit", "s"), ("value", "fast"),
                     ("value", -1.0), ("phase", None)):
        broken = dict(r)
        broken[key] = bad
        assert benchschema.validate_result(broken), (key, bad)
    # a full result missing its required numerics is caught
    full = {k: v for k, v in _full().items() if k != "batch"}
    assert any("batch" in p for p in benchschema.validate_result(full))
    # bool where a number is expected is caught (bool IS an int subclass)
    wrong = dict(_full())
    wrong["value"] = True
    assert benchschema.validate_result(wrong)


def _full():
    return bench.headline_result(
        rate=1.0, platform="cpu", batch=1, runs=1, warm_s=0.0,
        provegen_s=0.0, provegen_host_s=0.0, prove_txs=1, prove_rate=1.0,
        host_rate=1.0, prove_degraded=False, setup_s=0.0, stage_warmup_s=0.0,
    )


def _curve(effs):
    devs = [1, 2, 4, 8][: len(effs)]
    return [
        {"n_devices": d,
         "block_txs_per_s": round(0.1 * d * effs[i], 3),
         "efficiency": effs[i]}
        for i, d in enumerate(devs)
    ]


def test_scaling_curve_schema():
    """The throughput-vs-devices curve is schema-checked per row: a
    result carrying a valid curve passes, malformed curves are named."""
    r = _full()
    r["scaling"] = _curve([1.0, 0.9, 0.8, 0.7])
    assert benchschema.validate_result(r) == []
    assert benchschema.validate_scaling(r["scaling"]) == []
    # malformed shapes are caught
    assert benchschema.validate_scaling("not-a-list")
    assert benchschema.validate_scaling([])
    assert benchschema.validate_scaling([{"n_devices": 1}])  # missing fields
    dup = _curve([1.0, 0.9])
    dup[1]["n_devices"] = 1  # not strictly increasing
    assert benchschema.validate_scaling(dup)
    bad = _curve([1.0, 0.9])
    bad[0]["efficiency"] = "fast"
    assert benchschema.validate_scaling(bad)
    # a result with a broken curve fails result validation too
    r["scaling"] = bad
    assert benchschema.validate_result(r)


def _history_with_curves(tmp_path, eff_rows):
    path = str(tmp_path / "BENCH_history.jsonl")
    for effs in eff_rows:
        r = _full()
        if effs is not None:
            r["scaling"] = _curve(effs)
        bench.append_history(r, path=path)
    return path


def test_ftstop_scaling_gate(tmp_path, capsys):
    """`ftstop compare --scaling` reads multi-device rounds from the
    history, reports per-device efficiency, and exits 1 only when
    efficiency at the max device count regresses beyond the threshold."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftstop
    finally:
        sys.path.pop(0)

    # steady efficiency -> ok (rc 0); rounds without a curve are skipped
    path = _history_with_curves(
        tmp_path, [[1.0, 0.9, 0.85, 0.8], None, [1.0, 0.9, 0.84, 0.79]]
    )
    assert ftstop.main(["compare", "--history", path, "--scaling"]) == 0
    out = capsys.readouterr().out
    assert "n_devices=8" in out and "efficiency=" in out and "OK" in out

    # >10% efficiency drop at max devices -> regression, rc 1
    os.makedirs(tmp_path / "r", exist_ok=True)
    path = _history_with_curves(
        tmp_path / "r", [[1.0, 0.9, 0.85, 0.8], [1.0, 0.88, 0.8, 0.6]]
    )
    assert ftstop.main(["compare", "--history", path, "--scaling"]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    # --no-fail downgrades the exit code, not the verdict
    assert ftstop.main(
        ["compare", "--history", path, "--scaling", "--no-fail"]
    ) == 0

    # fewer than two curve-carrying rounds -> rc 2
    os.makedirs(tmp_path / "s", exist_ok=True)
    path = _history_with_curves(tmp_path / "s", [None, [1.0, 0.9]])
    assert ftstop.main(["compare", "--history", path, "--scaling"]) == 2


def _state(p99=0.002, pop=50000.0, rec=40000.0):
    return {
        "tokens": 1000000,
        "populate_s": 20.0,
        "populate_tokens_per_s": pop,
        "recover_s": 25.0,
        "recover_tokens_per_s": rec,
        "selector_p99_s": p99,
        "rss_high_water_mb": 900.0,
        "selects": 400,
        "spends": 1800,
        "threads": 4,
        "small_tokens": 10000,
        "selector_p99_small_s": 0.001,
        "sublinear_ratio": 2.0,
    }


def test_state_section_schema():
    """The state-plane scale section is field-checked like soak/scaling:
    a result carrying a valid section passes, malformed ones are named."""
    r = _full()
    r["state"] = _state()
    assert benchschema.validate_result(r) == []
    assert benchschema.validate_state(r["state"]) == []
    assert benchschema.validate_state("nope")
    assert benchschema.validate_state({})  # all required fields missing
    broken = _state()
    broken["selector_p99_s"] = "slow"
    assert benchschema.validate_state(broken)
    broken = _state()
    broken["tokens"] = -5
    assert any("negative" in p for p in benchschema.validate_state(broken))
    # nullable calibration fields stay valid as null
    ok = _state()
    ok["sublinear_ratio"] = None
    ok["selector_p99_small_s"] = None
    assert benchschema.validate_state(ok) == []
    # a result with a broken section fails result validation too
    r["state"] = broken
    assert benchschema.validate_result(r)


def _history_with_states(tmp_path, states):
    path = str(tmp_path / "BENCH_history.jsonl")
    for s in states:
        r = _full()
        if s is not None:
            r["state"] = s
        bench.append_history(r, path=path)
    return path


def test_ftstop_state_gate(tmp_path, capsys):
    """`ftstop compare --state` gates selector p99 (growth) and
    populate/recover throughput (drop) against the median of prior
    state-carrying rounds: rc 0 steady, rc 1 on regression, rc 2 when
    fewer than two rounds carry the section."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftstop
    finally:
        sys.path.pop(0)

    # steady numbers -> ok; state-less rounds are skipped
    path = _history_with_states(
        tmp_path, [_state(), None, _state(p99=0.0021)]
    )
    assert ftstop.main(["compare", "--history", path, "--state"]) == 0
    out = capsys.readouterr().out
    assert "state plane" in out and "selector_p99" in out and "OK" in out

    # p99 grows >10% -> regression rc 1 (direction-aware: growth is bad)
    os.makedirs(tmp_path / "p", exist_ok=True)
    path = _history_with_states(tmp_path / "p", [_state(), _state(p99=0.01)])
    assert ftstop.main(["compare", "--history", path, "--state"]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert ftstop.main(
        ["compare", "--history", path, "--state", "--no-fail"]
    ) == 0

    # recover throughput drops >10% -> regression too
    os.makedirs(tmp_path / "r", exist_ok=True)
    path = _history_with_states(tmp_path / "r", [_state(), _state(rec=1000.0)])
    assert ftstop.main(["compare", "--history", path, "--state"]) == 1

    # improvements never fail the gate
    os.makedirs(tmp_path / "i", exist_ok=True)
    path = _history_with_states(
        tmp_path / "i", [_state(), _state(p99=0.0001, pop=99999.0)]
    )
    assert ftstop.main(["compare", "--history", path, "--state"]) == 0

    # fewer than two state-carrying rounds -> rc 2
    os.makedirs(tmp_path / "s", exist_ok=True)
    path = _history_with_states(tmp_path / "s", [None, _state()])
    assert ftstop.main(["compare", "--history", path, "--state"]) == 2


def test_history_roundtrip_with_torn_tail(tmp_path):
    path = str(tmp_path / "BENCH_history.jsonl")
    assert bench.append_history(_full(), path=path) == path
    assert bench.append_history(
        bench.degraded_result("cpu", 8.0, {}), path=path
    ) == path
    with open(path, "a") as fh:
        fh.write('{"torn": ')  # crash mid-append
    rows = benchschema.load_history(path)
    assert len(rows) == 2  # torn tail skipped, like the WAL
    for row in rows:
        assert "ts" in row
        assert benchschema.validate_result(row) == []
    assert benchschema.is_degraded(rows[1]) and not benchschema.is_degraded(rows[0])

def _failover(loss=0, dups=0, p99=0.4, lag=3):
    return {
        "acked_tx_loss": loss,
        "duplicate_commits": dups,
        "failover_p99_s": p99,
        "follower_lag_max": lag,
        "acked_txs": 40,
        "killed_at_s": 6.0,
        "promoted_epoch": 1,
        "promotion": "auto",
        "failover_switches": 1,
        "stale_rejected": 2,
    }


def test_failover_section_schema():
    """The kill-the-leader soak section is field-checked like state/
    scaling: valid sections pass, malformed ones are named, and the
    contract fields reject negatives and bool-as-int."""
    r = _full()
    r["failover"] = _failover()
    assert benchschema.validate_result(r) == []
    assert benchschema.validate_failover(r["failover"]) == []
    assert benchschema.validate_failover("nope")
    assert benchschema.validate_failover({})  # required fields missing
    # p99 is nullable (no post-kill acks recorded -> null, still valid)
    ok = _failover()
    ok["failover_p99_s"] = None
    assert benchschema.validate_failover(ok) == []
    broken = _failover()
    broken["acked_tx_loss"] = -1
    assert any("negative" in p
               for p in benchschema.validate_failover(broken))
    broken = _failover()
    broken["duplicate_commits"] = True  # bool IS an int subclass
    assert benchschema.validate_failover(broken)
    broken = _failover()
    broken["follower_lag_max"] = "high"
    assert benchschema.validate_failover(broken)
    # a result with a broken section fails result validation too
    r["failover"] = broken
    assert benchschema.validate_result(r)


def _history_with_failovers(tmp_path, sections):
    path = str(tmp_path / "BENCH_history.jsonl")
    for s in sections:
        r = _full()
        if s is not None:
            r["failover"] = s
        bench.append_history(r, path=path)
    return path


def test_ftstop_failover_gate(tmp_path, capsys):
    """`ftstop compare --failover` layers an ABSOLUTE zero-tolerance
    check over the median gate: any nonzero acked_tx_loss or
    duplicate_commits in the latest round fails, even when every prior
    round was also zero (the rel-to-zero-baseline blind spot)."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftstop
    finally:
        sys.path.pop(0)

    # clean soaks -> ok; failover-less rounds are skipped
    path = _history_with_failovers(
        tmp_path, [_failover(), None, _failover(p99=0.42)]
    )
    assert ftstop.main(["compare", "--history", path, "--failover"]) == 0
    out = capsys.readouterr().out
    assert "failover" in out and "acked_tx_loss" in out and "OK" in out

    # zero-loss baseline, latest loses one acked tx: the relative gate
    # sees 0 -> 1 as rel 0.0, the absolute layer still fails it
    os.makedirs(tmp_path / "z", exist_ok=True)
    path = _history_with_failovers(
        tmp_path / "z", [_failover(), _failover(loss=1)]
    )
    assert ftstop.main(["compare", "--history", path, "--failover"]) == 1
    assert "absolute" in capsys.readouterr().out
    assert ftstop.main(
        ["compare", "--history", path, "--failover", "--no-fail"]
    ) == 0

    # duplicate commits are equally disqualifying
    os.makedirs(tmp_path / "d", exist_ok=True)
    path = _history_with_failovers(
        tmp_path / "d", [_failover(), _failover(dups=2)]
    )
    assert ftstop.main(["compare", "--history", path, "--failover"]) == 1

    # failover p99 growth beyond the threshold trips the median gate
    os.makedirs(tmp_path / "p", exist_ok=True)
    path = _history_with_failovers(
        tmp_path / "p", [_failover(), _failover(p99=5.0)]
    )
    assert ftstop.main(["compare", "--history", path, "--failover"]) == 1

    # fewer than two failover-carrying rounds -> rc 2
    os.makedirs(tmp_path / "s", exist_ok=True)
    path = _history_with_failovers(tmp_path / "s", [None, _failover()])
    assert ftstop.main(["compare", "--history", path, "--failover"]) == 2
