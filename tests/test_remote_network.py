"""TCP ledger node + remote parties: issue/transfer across the wire."""
import pytest

from fabric_token_sdk_tpu.api.driver import ValidationError
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.api.wallet import AuditorWallet
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.auditor import AuditorService
from fabric_token_sdk_tpu.services.network.ledger import Network, TxStatus
from fabric_token_sdk_tpu.services.network.remote import LedgerServer, RemoteNetwork
from fabric_token_sdk_tpu.services.ttx import Party, Transaction


def test_remote_ledger_flow():
    pp = FabTokenPublicParams()
    aw = AuditorWallet("auditor", sign.keygen())
    auditor = AuditorService(FabTokenDriver(pp), aw)
    server = LedgerServer(RequestValidator(FabTokenDriver(pp), aw.identity)).start()
    try:
        # two separate "processes": each party has its OWN RemoteNetwork client
        issuer_net, alice_net, bob_net = (RemoteNetwork(server.address) for _ in range(3))
        issuer_p = Party("issuer", FabTokenDriver(pp), issuer_net, aw.identity)
        alice_p = Party("alice", FabTokenDriver(pp), alice_net, aw.identity)
        bob_p = Party("bob", FabTokenDriver(pp), bob_net, aw.identity)
        iw = issuer_p.new_issuer_wallet("issuer")
        pp.add_issuer(iw.identity)
        alice = alice_p.new_owner_wallet("alice", False)
        bob = bob_p.new_owner_wallet("bob", False)

        tx = Transaction(issuer_p, "mint")
        tx.issue("issuer", "USD", [9], [alice.recipient_identity()], anonymous=False)
        tx.collect_endorsements(auditor)
        tx.submit()
        # receiver sync: alice's process replays the distributed request
        alice_net.apply_finality(tx.request.to_bytes())
        assert alice_p.balance("USD") == 9
        assert alice_net.height() == 1 and bob_net.height() == 1

        tx2 = Transaction(alice_p, "pay")
        tx2.transfer("alice", "USD", [4], [bob.recipient_identity()])
        tx2.collect_endorsements(auditor)
        tx2.submit()
        bob_net.apply_finality(tx2.request.to_bytes())
        assert bob_p.balance("USD") == 4
        assert alice_p.balance("USD") == 5

        # double spend across the wire is rejected by the server
        import dataclasses
        replay = dataclasses.replace(tx2.request, anchor="replay")
        auditor.audit(replay)
        ev = alice_net.submit(replay.to_bytes())
        assert ev.status == TxStatus.INVALID
        # resolving a spent token raises the typed error client-side
        with pytest.raises(ValidationError):
            bob_net.resolve_input(ID("mint", 0))
    finally:
        server.stop()


def test_ledger_snapshot_restore():
    pp = FabTokenPublicParams()
    aw = AuditorWallet("auditor", sign.keygen())
    auditor = AuditorService(FabTokenDriver(pp), aw)
    net = Network(RequestValidator(FabTokenDriver(pp), aw.identity))
    issuer_p = Party("issuer", FabTokenDriver(pp), net, aw.identity)
    alice_p = Party("alice", FabTokenDriver(pp), net, aw.identity)
    iw = issuer_p.new_issuer_wallet("issuer")
    pp.add_issuer(iw.identity)
    alice = alice_p.new_owner_wallet("alice", False)
    tx = Transaction(issuer_p, "mint")
    tx.issue("issuer", "USD", [5], [alice.recipient_identity()], anonymous=False)
    tx.collect_endorsements(auditor)
    tx.submit()

    snap = net.snapshot()
    net2 = Network.restore(RequestValidator(FabTokenDriver(pp), aw.identity), snap)
    assert net2.height() == 1
    assert net2.exists(ID("mint", 0))
    assert net2.status("mint").status == TxStatus.VALID
    # restored ledger still enforces MVCC
    assert net2.resolve_input(ID("mint", 0)) == net.resolve_input(ID("mint", 0))
