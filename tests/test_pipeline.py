"""Pipelined block engine + backpressure (PR 12 tentpole).

Differential identity against the sequential engine (verdicts, ledger
state, WAL contents — including under injected device faults), strict
height order under concurrency, verify/commit overlap accounting,
admission control with exactly-once retry semantics (local and over the
wire), condition-variable waits (CPU-time bounded), the prove→submit
client pipeline, and the soak observatory plumbing (schema + `ftstop
compare --soak`).
"""

import json
import os
import random
import sys
import threading
import time

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto.serialization import loads
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.services.network import (
    Backpressure,
    BlockPolicy,
    Network,
    TxStatus,
)
from fabric_token_sdk_tpu.services.network.remote import LedgerServer, RemoteNetwork
from fabric_token_sdk_tpu.services.network.wal import WriteAheadLog
from fabric_token_sdk_tpu.services.ttx import PipelinedSubmitter, Transaction
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx

from test_orderer import build_env, fab_env, issue_to, manual_transfer

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def _counter(name):
    return mx.REGISTRY.counter(name).value


def _wal_content(path):
    """Journal records minus the wall-clock stamp: the deterministic
    durable content two engines must agree on byte for byte."""
    return [
        {k: v for k, v in loads(raw).items() if k != "ts"}
        for raw in WriteAheadLog(path).replay()
    ]


def _policy(pipeline, **kw):
    kw.setdefault("max_block_txs", 2)
    return BlockPolicy(pipeline=pipeline, **kw)


# ===================================================================
# Differential: pipelined engine == sequential engine
# ===================================================================


def test_pipelined_vs_sequential_differential_fabtoken(tmp_path):
    """Same corpus (including an intra-block double spend) through both
    engines: identical verdicts, identical ledger state, identical WAL
    contents (modulo timestamps)."""
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=8))
    alice_p = parties["alice-node"]
    seed = issue_to(parties, alice, [5, 5, 7], "seed")
    ids = alice_p.vault.token_ids()
    reqs = [
        manual_transfer(alice_p, ids[0], 5, bob.recipient_identity(), "d-a"),
        manual_transfer(alice_p, ids[0], 5, bob.recipient_identity(), "d-b"),
        manual_transfer(alice_p, ids[1], 5, bob.recipient_identity(), "d-c"),
        manual_transfer(alice_p, ids[2], 7, bob.recipient_identity(), "d-d"),
    ]
    batch = [r.to_bytes() for r in reqs]
    pp = network.validator.driver.pp

    def run(pipeline):
        wal = str(tmp_path / f"wal-{int(pipeline)}.wal")
        net = Network(
            RequestValidator(FabTokenDriver(pp)),
            policy=_policy(pipeline),
            wal_path=wal,
        )
        assert (net._engine is not None) == pipeline
        ev0 = net.submit(seed.request.to_bytes())
        assert ev0.status == TxStatus.VALID
        events = net.submit_many(batch)
        from fabric_token_sdk_tpu.models.token import ID

        state = {
            a: net.exists(ID(a, 0)) for a in ("d-a", "d-b", "d-c", "d-d")
        }
        return (
            [(e.tx_id, e.status, e.message) for e in events],
            state,
            net.height(),
            _wal_content(wal),
        )

    piped = run(pipeline=True)
    seq = run(pipeline=False)
    assert piped == seq
    # the conflicting tx really was invalidated, in both
    assert piped[0][1][1] == TxStatus.INVALID
    # 1 seed block + ceil(4/2) blocks, strict height order in both
    assert piped[2] == 3
    # the journals carry the same heights in the same order
    assert [r["height"] for r in piped[3]] == [0, 1, 2]


def test_zk_pipelined_blocks_differential_and_metrics(zk_pp, tmp_path):
    """8 same-shape zkatdlog transfers streamed as two 4-tx blocks
    through the pipelined engine: verdicts, state and WAL contents match
    the sequential engine; the batched device plane carried every proof
    in both; the pipeline counters moved."""
    network, parties, issuer, alice, bob = build_env(
        lambda: ZKATDLogDriver(zk_pp), BlockPolicy(max_block_txs=16)
    )
    alice_p = parties["alice-node"]
    seed = issue_to(parties, alice, [5] * 8, "zkp-seed")
    reqs = [
        manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"zkp-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    batch = [r.to_bytes() for r in reqs]

    def run(pipeline):
        wal = str(tmp_path / f"zk-wal-{int(pipeline)}.wal")
        net = Network(
            RequestValidator(ZKATDLogDriver(zk_pp)),
            policy=_policy(pipeline, max_block_txs=4, min_batch=2),
            wal_path=wal,
        )
        before_bt = _counter("batch.transfer.txs")
        ev0 = net.submit(seed.request.to_bytes())
        assert ev0.status == TxStatus.VALID
        events = net.submit_many(batch)
        assert _counter("batch.transfer.txs") - before_bt == 8
        return (
            [(e.tx_id, e.status, e.message) for e in events],
            net.height(),
            _wal_content(wal),
        )

    blocks_before = _counter("orderer.pipeline.blocks")
    piped = run(pipeline=True)
    piped_blocks = _counter("orderer.pipeline.blocks") - blocks_before
    seq = run(pipeline=False)
    assert piped == seq
    assert all(s == TxStatus.VALID for _t, s, _m in piped[0])
    assert piped[1] == 3  # seed block + 2 transfer blocks, height-ordered
    # the transfer blocks (and the seed block) rode the engine
    assert piped_blocks >= 3
    # the sequential run routed around it entirely
    assert _counter("orderer.pipeline.blocks") - blocks_before == piped_blocks


def test_pipelined_batch_verify_fault_degrades_identically(zk_pp):
    """An injected `batch.verify` fault inside a PIPELINED block falls
    back to host validation with identical verdicts — the degrade chain
    survives the overlap."""

    def run(inject):
        net, parties, issuer, alice, bob = build_env(
            lambda: ZKATDLogDriver(zk_pp),
            BlockPolicy(max_block_txs=8, min_batch=2, pipeline=True),
        )
        assert net._engine is not None
        issue_to(parties, alice, [5, 5], f"pf-seed-{int(inject)}")
        alice_p = parties["alice-node"]
        reqs = [
            manual_transfer(alice_p, tid, 5, bob.recipient_identity(),
                            f"pf-{int(inject)}-{i}")
            for i, tid in enumerate(alice_p.vault.token_ids())
        ]
        if inject:
            faults.arm("batch.verify", "error", count=1)
        try:
            events = net.submit_many([r.to_bytes() for r in reqs])
        finally:
            faults.clear()
        return [e.status for e in events], parties["bob-node"].balance("USD")

    errors_before = _counter("ledger.block.batch_errors")
    host_before = _counter("ledger.validate.host")
    injected = run(inject=True)
    assert _counter("ledger.block.batch_errors") - errors_before == 1
    assert _counter("ledger.validate.host") - host_before == 2
    clean = run(inject=False)
    assert injected == clean == ([TxStatus.VALID, TxStatus.VALID], 10)


def test_pipeline_kill_switch_restores_sequential(monkeypatch):
    """FTS_BLOCK_PIPELINE=0 beats even an explicit pipeline=True policy:
    no engine, no worker, no overlap_s in the breakdown — the exact old
    path."""
    monkeypatch.setenv("FTS_BLOCK_PIPELINE", "0")
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=4, pipeline=True)
    )
    assert network._engine is None
    issue_to(parties, alice, [5], "ks-seed")
    assert "overlap_s" not in network.last_block["breakdown"]


def test_pipelined_commit_error_reaches_the_waiter(tmp_path):
    """A commit-stage exception on the worker thread (injected WAL
    fault) re-raises on the waiter's stack — the sequential engine's
    driving-thread contract — and nothing durable is recorded."""
    wal = str(tmp_path / "err.wal")
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=8))
    pp = network.validator.driver.pp
    net = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=_policy(True, max_block_txs=8),
        wal_path=wal,
    )
    issue_to(parties, alice, [5], "seed")
    alice_p = parties["alice-node"]
    tid = alice_p.vault.token_ids()[0]
    req = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), "we-pay")
    faults.arm("wal.append", "error", count=1)
    try:
        with pytest.raises(faults.FaultInjected):
            net.submit(req.to_bytes())
    finally:
        faults.clear()
    assert net.status("we-pay") is None and net.height() == 0
    # fault expended: an identical resubmission commits exactly once
    assert net.submit(req.to_bytes()).status == TxStatus.INVALID  # no seed
    assert net.height() == 1


def test_pipelined_height_order_under_concurrency():
    """Concurrent submitters through the engine: every tx lands in
    exactly one block, block numbers are strictly sequential, balances
    conserve."""
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=2, pipeline=True)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2] * 6, "seed")
    reqs = [
        manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"hc-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    h0 = network.height()
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(len(reqs))

    def worker(rb):
        barrier.wait()
        ev = network.submit(rb)
        with lock:
            results.append(ev)

    threads = [
        threading.Thread(target=worker, args=(r.to_bytes(),)) for r in reqs
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(e.status == TxStatus.VALID for e in results)
    committed = []
    for i in range(h0, network.height()):
        block = network.block(i)
        assert block.number == i  # strict height order at the merge point
        committed.extend(block.txs)
    assert sorted(committed) == sorted(f"hc-{i}" for i in range(len(reqs)))
    assert parties["bob-node"].balance("USD") == 12


# ===================================================================
# Overlap accounting + condition-variable waits
# ===================================================================


def test_overlap_recorded_when_commit_is_slow():
    """With an artificially slow commit stage, block N+1's verify runs
    almost entirely inside block N's commit window: `overlap_s` lands in
    the breakdown and the overlap gauge/histogram move."""
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=1, pipeline=True)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2, 2, 2], "seed")
    reqs = [
        manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"ov-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    hist = mx.REGISTRY.histogram("orderer.pipeline.overlap.seconds")
    count_before, sum_before = hist.count, hist.sum
    faults.arm("ledger.commit_block", "delay", delay_s=0.15)
    try:
        events = network.submit_many([r.to_bytes() for r in reqs])
    finally:
        faults.clear()
    assert all(e.status == TxStatus.VALID for e in events)
    assert hist.count - count_before >= 3  # one observation per block
    # at least one later block's verify ran inside an earlier block's
    # commit window (the first block of a burst never can)
    assert hist.sum - sum_before > 0
    # the breakdown carries the overlap leg in pipelined mode
    assert "overlap_s" in network.last_block["breakdown"]


def test_waiters_park_without_burning_cpu():
    """Satellite: waiters on an in-flight block wait on a condition
    variable, not a busy-race on the commit lock — process CPU time
    during a slow commit stays far below wall time."""
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=8, pipeline=True)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2, 2], "seed")
    reqs = [
        manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"cw-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    subs = [network.submit_async(r.to_bytes()) for r in reqs]
    faults.arm("ledger.commit_block", "delay", delay_s=0.5)
    waiters_done = []

    def waiter(s):
        waiters_done.append(s.result(timeout=30))

    try:
        threads = [
            threading.Thread(target=waiter, args=(s,)) for s in subs
        ]
        wall0, cpu0 = time.monotonic(), time.process_time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall, cpu = time.monotonic() - wall0, time.process_time() - cpu0
    finally:
        faults.clear()
    assert all(e.status == TxStatus.VALID for e in waiters_done)
    assert wall >= 0.45  # the injected delay really gated the block
    # a busy-race would burn ~wall seconds of CPU across the waiters
    assert cpu < 0.6 * wall, f"waiters burned {cpu:.2f}s CPU in {wall:.2f}s"


# ===================================================================
# Backpressure: admission control + exactly-once retry
# ===================================================================


def test_backpressure_rejects_before_ordering():
    """A full ordering queue rejects with the typed error BEFORE the tx
    enters ordering: nothing committed, nothing recorded, a later retry
    lands exactly once."""
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=8, queue_max=2)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2, 2, 2], "seed")
    reqs = [
        manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"bp-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    rejects_before = _counter("orderer.backpressure.rejects")
    s0 = network.submit_async(reqs[0].to_bytes())
    s1 = network.submit_async(reqs[1].to_bytes())
    with pytest.raises(Backpressure):
        network.submit_async(reqs[2].to_bytes())
    assert _counter("orderer.backpressure.rejects") - rejects_before == 1
    assert network.status("bp-2") is None  # never entered ordering
    network.flush()
    assert s0.result().status == TxStatus.VALID
    assert s1.result().status == TxStatus.VALID
    # retry after drain: exactly one commit, no resubmission dedup needed
    resub_before = _counter("network.submit.resubmissions")
    assert network.submit(reqs[2].to_bytes()).status == TxStatus.VALID
    assert _counter("network.submit.resubmissions") == resub_before


def test_submit_many_is_cooperative_under_backpressure():
    """A batch larger than the queue bound lands WHOLE: the batch
    submitter drains its own queue on each rejection instead of
    stranding the enqueued prefix."""
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=2, queue_max=2)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2] * 6, "seed")
    reqs = [
        manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"co-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    flushes_before = _counter("orderer.backpressure.flushes")
    events = network.submit_many([r.to_bytes() for r in reqs])
    assert [e.status for e in events] == [TxStatus.VALID] * 6
    assert _counter("orderer.backpressure.flushes") > flushes_before
    assert parties["bob-node"].balance("USD") == 12


def test_remote_backpressure_exactly_once_with_backoff():
    """Satellite acceptance: a remote client that receives the typed
    `Backpressure` retries with backoff and lands EXACTLY one commit —
    counter-asserted (one valid tx, zero dedup'd resubmissions)."""
    network, parties, issuer, alice, bob = fab_env(
        BlockPolicy(max_block_txs=8, queue_max=1)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2, 5], "seed")
    ids = alice_p.vault.token_ids()
    blocker = manual_transfer(alice_p, ids[0], 2, bob.recipient_identity(),
                              "rbp-blocker")
    payed = manual_transfer(alice_p, ids[1], 5, bob.recipient_identity(),
                            "rbp-pay")
    server = LedgerServer(network=network).start()
    client = RemoteNetwork(server.address, retries=8, backoff_s=0.05)
    try:
        # fill the 1-deep queue so the wire submit is rejected
        blocked = network.submit_async(blocker.to_bytes())
        retry_before = _counter("remote.retry.backpressure")
        valid_before = _counter("network.tx.valid")
        resub_before = _counter("network.submit.resubmissions")

        def drain_later():
            time.sleep(0.25)
            network.flush()

        t = threading.Thread(target=drain_later)
        t.start()
        event = client.submit(payed.to_bytes())
        t.join()
        assert blocked.result(timeout=10).status == TxStatus.VALID
    finally:
        client.close()
        server.stop()
    assert event.status == TxStatus.VALID
    assert _counter("remote.retry.backpressure") - retry_before >= 1
    # exactly once: both txs committed once, nothing was dedup'd
    assert _counter("network.tx.valid") - valid_before == 2
    assert _counter("network.submit.resubmissions") == resub_before
    assert network.status("rbp-pay").status == TxStatus.VALID


# ===================================================================
# Prove→submit overlap: the pipelined ttx client path
# ===================================================================


def test_pipelined_submitter_overlaps_prove_with_submit():
    """While group k is in flight (slow commit), the caller is already
    building group k+1: results come back in order, all valid, and the
    overlap gauge records that proving ran during submission."""
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=8))
    issuer_p = parties["issuer-node"]

    def builder(gi):
        def build():
            time.sleep(0.05)  # stands in for BatchedTransferProver work
            out = []
            for j in range(2):
                t = Transaction(issuer_p, f"ps-{gi}-{j}")
                t.issue("issuer", "USD", [1 + gi],
                        [alice.recipient_identity()], anonymous=False)
                t.collect_endorsements(None)
                out.append(t.request.to_bytes())
            return out

        return build

    groups_before = _counter("ttx.pipeline.groups")
    faults.arm("ledger.commit_block", "delay", delay_s=0.1)
    try:
        results = PipelinedSubmitter(network).run(
            [builder(i) for i in range(3)]
        )
    finally:
        faults.clear()
    assert len(results) == 3
    for gi, events in enumerate(results):
        assert [e.tx_id for e in events] == [f"ps-{gi}-{j}" for j in range(2)]
        assert all(e.status == TxStatus.VALID for e in events)
    assert _counter("ttx.pipeline.groups") - groups_before == 3
    assert mx.REGISTRY.gauge("ttx.pipeline.overlap_frac").value > 0


def test_pipelined_submitter_retries_backpressure():
    """A `Backpressure` raised by the network is retried with backoff
    inside the submit worker — the pipeline never loses a group."""
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=8))
    issuer_p = parties["issuer-node"]
    calls = {"n": 0}
    real = network.submit_many

    def flaky(requests):
        calls["n"] += 1
        if calls["n"] == 1:
            raise Backpressure("synthetic queue-full")
        return real(requests)

    network.submit_many = flaky
    bp_before = _counter("ttx.pipeline.backpressure")

    def build():
        t = Transaction(issuer_p, "psb-0")
        t.issue("issuer", "USD", [3], [alice.recipient_identity()],
                anonymous=False)
        t.collect_endorsements(None)
        return [t.request.to_bytes()]

    results = PipelinedSubmitter(network, backoff_s=0.01).run([build])
    assert [e.status for e in results[0]] == [TxStatus.VALID]
    assert _counter("ttx.pipeline.backpressure") - bp_before == 1


# ===================================================================
# Soak observatory plumbing: schema + ftstop gates + top rendering
# ===================================================================


def _ftstop():
    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftstop
    finally:
        sys.path.pop(0)
    return ftstop


def _full_result(**over):
    import bench

    r = bench.headline_result(
        rate=100.0, platform="cpu", batch=8, runs=1, warm_s=1.0,
        provegen_s=2.0, provegen_host_s=0.5, prove_txs=4, prove_rate=2.0,
        host_rate=1.0, prove_degraded=False, setup_s=0.1, stage_warmup_s=5.0,
    )
    r.update(over)
    return r


def _soak_section(**over):
    s = {"steady_txs_per_s": 120.0, "p99_finality_s": 0.8,
         "queue_depth_max": 40, "backpressure_rejects": 3}
    s.update(over)
    return s


def test_soak_schema_validates():
    from fabric_token_sdk_tpu.utils import benchschema

    r = _full_result()
    r["soak"] = _soak_section()
    assert benchschema.validate_result(r) == []
    assert benchschema.validate_soak(r["soak"]) == []
    # p99 is nullable (a soak that committed nothing)
    assert benchschema.validate_soak(_soak_section(p99_finality_s=None)) == []
    # malformed sections are named
    assert benchschema.validate_soak("fast")
    assert benchschema.validate_soak({})
    assert benchschema.validate_soak(_soak_section(steady_txs_per_s=-1.0))
    assert benchschema.validate_soak(_soak_section(backpressure_rejects=0.5))
    r["soak"] = {"steady_txs_per_s": 1.0}
    assert benchschema.validate_result(r)  # incomplete soak fails the result


def test_ftstop_soak_gate(tmp_path, capsys):
    """`ftstop compare --soak` gates steady-state tx/s (drop = regress)
    and p99 finality (growth = regress) against the median of prior
    soak-carrying rounds."""
    import bench

    ftstop = _ftstop()

    def history(rows, sub):
        path = str(tmp_path / sub / "hist.jsonl")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        for soak in rows:
            r = _full_result()
            if soak is not None:
                r["soak"] = soak
            bench.append_history(r, path=path)
        return path

    # steady numbers -> ok; rounds without a soak section are skipped
    path = history(
        [_soak_section(), None, _soak_section(steady_txs_per_s=118.0)], "a"
    )
    assert ftstop.main(["compare", "--history", path, "--soak"]) == 0
    out = capsys.readouterr().out
    assert "soak.steady_txs_per_s" in out and "OK" in out

    # throughput collapse -> regression, rc 1; --no-fail reports only
    path = history(
        [_soak_section(), _soak_section(steady_txs_per_s=50.0)], "b"
    )
    assert ftstop.main(["compare", "--history", path, "--soak"]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert ftstop.main(
        ["compare", "--history", path, "--soak", "--no-fail"]
    ) == 0

    # p99 finality blow-up alone is also a regression
    path = history(
        [_soak_section(), _soak_section(p99_finality_s=2.5)], "c"
    )
    assert ftstop.main(["compare", "--history", path, "--soak"]) == 1
    capsys.readouterr()

    # fewer than two soak-carrying rounds -> rc 2
    path = history([None, _soak_section()], "d")
    assert ftstop.main(["compare", "--history", path, "--soak"]) == 2


def test_ftstop_top_renders_queue_trend_and_backpressure():
    ftstop = _ftstop()
    health = {"uptime_s": 5.0, "height": 3, "queue_depth": 7, "inflight": 9}
    prev = {
        "counters": {"network.tx.valid": 10,
                     "orderer.backpressure.rejects": 2},
        "gauges": {"orderer.queue.depth": 4},
    }
    snap = {
        "counters": {"network.tx.valid": 30,
                     "orderer.backpressure.rejects": 6},
        "gauges": {"orderer.queue.depth": 7},
    }
    row = ftstop.format_row(health, snap, prev, 2.0)
    assert "queue=7(+3)" in row
    assert "bp/s=2.00" in row
    assert "tx/s=10.00" in row
    # no previous poll: trend and rates degrade to placeholders
    row0 = ftstop.format_row(health, snap, None, None)
    assert "queue=7 " in row0 + " " and "bp/s=-" in row0


def test_bench_soak_phase_smoke(monkeypatch):
    """The bench soak phase end to end (tiny budget): a parsed section
    with steady tx/s, client p99, bounded queue depth — schema-valid."""
    import bench
    from fabric_token_sdk_tpu.utils import benchschema

    monkeypatch.setenv("FTS_BENCH_SOAK_S", "1.5")
    monkeypatch.setenv("FTS_BENCH_SOAK_CLIENTS", "2")
    monkeypatch.setenv("FTS_BENCH_SOAK_GROUP", "4")
    monkeypatch.setenv("FTS_BENCH_SOAK_QUEUE_MAX", "16")

    class _HB:
        def set_phase(self, *a, **k):
            pass

    soak = bench._soak(_HB())
    assert benchschema.validate_soak(soak) == []
    assert soak["steady_txs_per_s"] > 0
    assert soak["txs"] > 0
    assert soak["p99_finality_s"] > 0
    assert soak["queue_depth_max"] <= 16
