"""Driver tests: fabtoken + zkatdlog end-to-end issue/transfer/redeem."""
import random
import pytest

from fabric_token_sdk_tpu.api.request import TokenRequest
from fabric_token_sdk_tpu.api.tms import ManagementService
from fabric_token_sdk_tpu.api.driver import ValidationError
from fabric_token_sdk_tpu.api.wallet import IssuerWallet, OwnerWallet, WalletRegistry
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.models.token import ID


def make_ledger(outputs_by_id):
    def resolve(token_id):
        if token_id not in outputs_by_id:
            raise ValidationError(f"unknown input {token_id}")
        return outputs_by_id[token_id]
    return resolve


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def run_lifecycle(tms, alice, bob, issuer, anonymous):
    # issue 12 to alice
    req = tms.new_request("tx1")
    alice_id = alice.recipient_identity()
    tms.add_issue(req, issuer, "USD", [12], [alice_id], anonymous=anonymous)
    tms.sign_issues(req)
    v = tms.validator()
    result = v.validate(req, make_ledger({}))
    (kind, outputs), = result.outputs
    assert kind == "issue" and len(outputs) == 1

    ledger = {ID("tx1", 0): outputs[0]}
    meta = req.issues[0].outputs_metadata

    # transfer 12 -> 7 (bob) + 5 (alice change)
    req2 = tms.new_request("tx2")
    bob_id = bob.recipient_identity()
    change_id = alice.recipient_identity()
    tms.add_transfer(
        req2, [ID("tx1", 0)], [ledger[ID("tx1", 0)]], meta, "USD", [7, 5],
        [bob_id, change_id],
    )
    tms.sign_transfers(req2)
    res2 = v.validate(req2, make_ledger(ledger))
    assert res2.spent == [ID("tx1", 0)]
    (_, outs2), = res2.outputs
    ledger2 = {ID("tx2", 0): outs2[0], ID("tx2", 1): outs2[1]}

    # bob's token opens correctly
    ut = tms.driver.output_to_unspent(
        ID("tx2", 0), outs2[0], req2.transfers[0].outputs_metadata[0]
    )
    assert ut.type == "USD" and ut.quantity == "7"

    # redeem bob's 7 -> redeem 4 + change 3
    req3 = tms.new_request("tx3")
    tms.add_redeem(
        req3, [ID("tx2", 0)], [outs2[0]], [req2.transfers[0].outputs_metadata[0]],
        "USD", 4, 3, bob.recipient_identity(),
    )
    tms.sign_transfers(req3)
    res3 = v.validate(req3, make_ledger(ledger2))
    (_, outs3), = res3.outputs
    assert tms.driver.output_owner(outs3[0]) == b""  # redeemed output

    # double spend within one request is rejected
    req4 = tms.new_request("tx4")
    tms.add_transfer(req4, [ID("tx2", 1)], [outs2[1]],
                     [req2.transfers[0].outputs_metadata[1]], "USD", [5],
                     [bob.recipient_identity()])
    tms.add_transfer(req4, [ID("tx2", 1)], [outs2[1]],
                     [req2.transfers[0].outputs_metadata[1]], "USD", [5],
                     [bob.recipient_identity()])
    tms.sign_transfers(req4)
    with pytest.raises(ValidationError):
        v.validate(req4, make_ledger(ledger2))

    # wrong signature is rejected
    req5 = tms.new_request("tx5")
    tms.add_transfer(req5, [ID("tx2", 1)], [outs2[1]],
                     [req2.transfers[0].outputs_metadata[1]], "USD", [5],
                     [bob.recipient_identity()])
    tms.sign_transfers(req5)
    req5.transfers[0].signatures[0] = req3.transfers[0].signatures[0]
    with pytest.raises(ValidationError):
        v.validate(req5, make_ledger(ledger2))


def test_fabtoken_lifecycle(rng):
    driver = FabTokenDriver(FabTokenPublicParams())
    wallets = WalletRegistry()
    alice = OwnerWallet("alice", anonymous=False, rng=rng)
    bob = OwnerWallet("bob", anonymous=False, rng=rng)
    issuer = IssuerWallet("issuer", sign.keygen(rng))
    wallets.owners = {"alice": alice, "bob": bob}
    wallets.issuers = {"issuer": issuer}
    driver.pp.add_issuer(issuer.identity)
    tms = ManagementService(driver, wallets, rng=rng)
    run_lifecycle(tms, alice, bob, issuer, anonymous=False)


def test_fabtoken_unauthorized_issuer(rng):
    driver = FabTokenDriver(FabTokenPublicParams())
    issuer = IssuerWallet("issuer", sign.keygen(rng))
    rogue = IssuerWallet("rogue", sign.keygen(rng))
    driver.pp.add_issuer(issuer.identity)
    wallets = WalletRegistry()
    wallets.issuers = {"rogue": rogue}
    alice = OwnerWallet("alice", anonymous=False, rng=rng)
    wallets.owners = {"alice": alice}
    tms = ManagementService(driver, wallets, rng=rng)
    req = tms.new_request("tx1")
    tms.add_issue(req, rogue, "USD", [5], [alice.recipient_identity()], anonymous=False)
    tms.sign_issues(req)
    with pytest.raises(ValidationError):
        tms.validator().validate(req, make_ledger({}))


def test_zkatdlog_lifecycle(rng, zk_pp):
    driver = ZKATDLogDriver(zk_pp)
    wallets = WalletRegistry()
    alice = OwnerWallet("alice", anonymous=True, nym_params=zk_pp.nym_params, rng=rng)
    bob = OwnerWallet("bob", anonymous=True, nym_params=zk_pp.nym_params, rng=rng)
    issuer = IssuerWallet("issuer", sign.keygen(rng))
    wallets.owners = {"alice": alice, "bob": bob}
    wallets.issuers = {"issuer": issuer}
    tms = ManagementService(driver, wallets, rng=rng)
    run_lifecycle(tms, alice, bob, issuer, anonymous=True)


def test_zkatdlog_value_out_of_range(rng, zk_pp):
    driver = ZKATDLogDriver(zk_pp)
    issuer = IssuerWallet("issuer", sign.keygen(rng))
    with pytest.raises(ValueError):
        driver.issue(issuer.identity, "USD", [zk_pp.max_token_value() + 1], [b"x"])


def test_issue_authorization_cannot_be_bypassed(rng):
    """Record-level issuer swap / blanking must not bypass the action's
    issuer signature requirement."""
    driver = FabTokenDriver(FabTokenPublicParams())
    issuer = IssuerWallet("issuer", sign.keygen(rng))
    rogue = IssuerWallet("rogue", sign.keygen(rng))
    driver.pp.add_issuer(issuer.identity)
    wallets = WalletRegistry()
    wallets.issuers = {"rogue": rogue}
    alice = OwnerWallet("alice", anonymous=False, rng=rng)
    wallets.owners = {"alice": alice}
    tms = ManagementService(driver, wallets, rng=rng)
    req = tms.new_request("tx1")
    # forge: action names the AUTHORIZED issuer, record claims the rogue
    outcome = driver.issue(issuer.identity, "USD", [5],
                           [alice.recipient_identity()], anonymous=False)
    from fabric_token_sdk_tpu.api.request import IssueRecord
    rec = IssueRecord(action=outcome.action_bytes, issuer=rogue.identity,
                      outputs_metadata=outcome.metadata)
    req.issues.append(rec)
    rec.signature = rogue.sign(req.marshal_to_sign(), rng)
    with pytest.raises(ValidationError):
        tms.validator().validate(req, make_ledger({}))
    # blanking the record issuer must not skip the check either
    rec.issuer = b""
    rec.signature = b""
    with pytest.raises(ValidationError):
        tms.validator().validate(req, make_ledger({}))


def test_malformed_action_bytes_rejected(rng):
    driver = FabTokenDriver(FabTokenPublicParams())
    with pytest.raises(ValidationError):
        driver.validate_issue(b"garbage")
    from fabric_token_sdk_tpu.crypto.serialization import dumps
    with pytest.raises(ValidationError):
        driver.validate_issue(dumps({"nope": 1}))
    with pytest.raises(ValidationError):
        driver.validate_transfer(dumps({"ids": [["a", 0]], "inputs": [], "outputs": []}),
                                 make_ledger({}), b"", [])
