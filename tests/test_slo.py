"""SLO engine: window math, burn/budget rows, breach events, slow-tx
exemplars, the extended bench schema sections, and the `compare --slo`
gate.

Also pins the Histogram invariant the engine depends on: adding the
windowed `state()`/`fraction_le` readers changed NOTHING about the
cumulative `snapshot()`/`to_prometheus()` output (byte-stability).
"""
import argparse
import json
import os
import sys

import pytest

from fabric_token_sdk_tpu.utils import benchschema
from fabric_token_sdk_tpu.utils import metrics as mx
from fabric_token_sdk_tpu.utils import slo

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "cmd"))
import ftstop  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_engine():
    slo.reset()
    yield
    slo.reset()


# ===================================================================
# fraction_le: the windowed bucket-delta quantile primitive
# ===================================================================


def test_fraction_le_basics():
    buckets = (0.1, 1.0, 10.0)
    # counts per bucket: <=0.1: 6, <=1.0: 2, <=10.0: 1, +Inf: 1
    counts = [6, 2, 1, 1]
    f = mx.Histogram.fraction_le
    assert f(buckets, [0, 0, 0, 0], 1.0) is None  # no traffic
    assert f(buckets, counts, 0.1) == pytest.approx(0.6)
    assert f(buckets, counts, 1.0) == pytest.approx(0.8)
    assert f(buckets, counts, 10.0) == pytest.approx(0.9)
    # interpolation inside a bucket: halfway through (0.1, 1.0]
    assert f(buckets, counts, 0.55) == pytest.approx(0.7)
    # the +Inf bucket is never good, whatever the threshold
    assert f(buckets, counts, 1e9) == pytest.approx(0.9)
    # below the first bucket: nothing provably good
    assert f(buckets, counts, 0.0) == pytest.approx(0.0)


def test_fraction_le_matches_observed_stream():
    h = mx.Histogram("slo.check", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 2.0):
        h.observe(v)
    counts, count, _s = h.state()
    assert count == 5
    assert mx.Histogram.fraction_le(h.buckets, counts, 0.1) == pytest.approx(
        3 / 5
    )


# ===================================================================
# engine rows: latency, availability, breach transition
# ===================================================================


def _drive(engine, finality_obs=(), commit_obs=(), bp=0, enq=0):
    for v in finality_obs:
        mx.REGISTRY.histogram(slo._HIST_FINALITY).observe(v)
    for v in commit_obs:
        mx.REGISTRY.histogram(slo._HIST_COMMIT).observe(v)
    if enq:
        mx.REGISTRY.counter(slo._CTR_ENQUEUED).inc(enq)
    if bp:
        mx.REGISTRY.counter(slo._CTR_BACKPRESSURE).inc(bp)
    return engine.evaluate()


def test_healthy_window_is_ok():
    engine = slo.reset(window_s=60.0, finality_p99_s=1.0, commit_p99_s=1.0)
    out = _drive(engine, finality_obs=[0.01] * 50, commit_obs=[0.02] * 5,
                 enq=50)
    assert out["window_s"] == 60.0
    for name in ("finality_p99", "commit_p99", "availability"):
        row = out["slos"][name]
        assert row["ok"] is True, (name, row)
        assert row["burn"] < 1.0
        assert row["budget_remaining"] > 0.0
    assert out["slos"]["finality_p99"]["target_s"] == 1.0
    assert out["slos"]["finality_p99"]["total"] == 50
    assert out["slos"]["availability"]["total"] == 50


def test_empty_window_burns_nothing():
    engine = slo.reset(window_s=60.0)
    out = engine.evaluate()
    for row in out["slos"].values():
        assert row["ok"] is True
        assert row["burn"] == 0.0
        assert row["good_frac"] is None
        assert row["total"] == 0


def test_slow_tail_breaches_and_emits_flight_once():
    engine = slo.reset(window_s=60.0, finality_p99_s=0.1)
    breaches0 = mx.REGISTRY.counter("slo.breaches").value
    # 10% of txs blow the 100ms target: good_frac 0.9 << 0.99 objective
    out = _drive(engine, finality_obs=[0.01] * 9 + [5.0], enq=10)
    row = out["slos"]["finality_p99"]
    assert row["ok"] is False
    assert row["burn"] >= 1.0
    assert row["budget_remaining"] == 0.0
    assert mx.REGISTRY.counter("slo.breaches").value == breaches0 + 1
    evt = [e for e in mx.FLIGHT.tail() if e["kind"] == "slo.breach"][-1]
    assert evt["slo"] == "finality_p99"
    assert evt["burn"] >= 1.0
    # still breaching: no second transition, no second flight event
    engine._last_tick = 0.0
    out = _drive(engine, finality_obs=[5.0], enq=1)
    assert out["slos"]["finality_p99"]["ok"] is False
    assert mx.REGISTRY.counter("slo.breaches").value == breaches0 + 1
    # burn/budget gauges track the live row
    assert mx.REGISTRY.gauge("slo.burn.finality_p99").value >= 1.0
    assert mx.REGISTRY.gauge("slo.budget.finality_p99").value == 0.0


def test_availability_counts_backpressure_as_bad():
    engine = slo.reset(window_s=60.0, availability=0.9)
    out = _drive(engine, enq=8, bp=2)  # 8 admitted of 10 attempts
    row = out["slos"]["availability"]
    assert row["total"] == 10
    assert row["good_frac"] == pytest.approx(0.8)
    assert row["ok"] is False  # 20% shed >> the 10% budget
    out = _drive(engine, enq=1)  # within the SAME window: still bad
    assert out["slos"]["availability"]["ok"] is False


def test_health_section_rides_network_health():
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.drivers.fabtoken import (
        FabTokenDriver, FabTokenPublicParams,
    )
    from fabric_token_sdk_tpu.services.network import Network

    net = Network(RequestValidator(FabTokenDriver(FabTokenPublicParams())))
    h = net.health()
    assert set(h["slo"]["slos"]) == {
        "finality_p99", "commit_p99", "availability",
    }


# ===================================================================
# slow-tx exemplars
# ===================================================================


def test_exemplar_ring_keeps_k_slowest_in_order(monkeypatch):
    monkeypatch.setenv("FTS_SLO_EXEMPLARS", "3")
    for i, s in enumerate([0.1, 0.5, 0.3, 0.9, 0.2, 0.7]):
        slo.record_exemplar(s, f"tx-{i}", f"tr-{i}")
    top = slo.exemplars()
    assert [t[0] for t in top] == [0.9, 0.7, 0.5]
    assert [t[1] for t in top] == ["tx-3", "tx-5", "tx-1"]
    # published into registry meta for the sidecar / ftsmetrics show
    meta = mx.REGISTRY.snapshot()["meta"]["slo.exemplars"]
    assert meta[0][1] == "tx-3" and meta[0][2] == "tr-3"


def test_exemplars_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("FTS_SLO_EXEMPLARS", "0")
    slo.record_exemplar(9.0, "tx-x", None)
    assert slo.exemplars() == []


def test_finality_resolution_records_exemplars():
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.drivers.fabtoken import (
        FabTokenDriver, FabTokenPublicParams,
    )
    from fabric_token_sdk_tpu.services.network import Network
    from fabric_token_sdk_tpu.services.ttx import Party, Transaction

    pp = FabTokenPublicParams()
    net = Network(RequestValidator(FabTokenDriver(pp)))
    party = Party("issuer-node", FabTokenDriver(pp), net)
    party.new_issuer_wallet("issuer")
    owner = party.new_owner_wallet("self", anonymous=False)
    tx = Transaction(party, "slo-seed")
    tx.issue("issuer", "USD", [3], [owner.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(None)
    tx.submit()
    assert any(t[1] == "slo-seed" for t in slo.exemplars())


# ===================================================================
# histogram byte-stability: windowed readers change no cumulative output
# ===================================================================


def test_snapshot_and_prometheus_unchanged_by_windowed_readers():
    obs = (0.004, 0.03, 0.03, 0.7, 12.0)

    def build():
        h = mx.Histogram("net.check.seconds")
        for v in obs:
            h.observe(v)
        return h

    virgin = build()
    snap_before = json.dumps(virgin.snapshot(), sort_keys=True)

    probed = build()
    # exercise the new read-only surface between observes and snapshot
    state = probed.state()
    assert state[1] == len(obs)
    mx.Histogram.fraction_le(probed.buckets, state[0], 0.05)
    probed.observe  # attribute access only; no further observes
    snap_after = json.dumps(probed.snapshot(), sort_keys=True)
    assert snap_before == snap_after

    # Prometheus exposition is byte-identical too (same registry name)
    reg_a, reg_b = mx.Registry(), mx.Registry()
    for v in obs:
        reg_a.histogram("net.check.seconds").observe(v)
        reg_b.histogram("net.check.seconds").observe(v)
    reg_b.histogram("net.check.seconds").state()
    assert reg_a.to_prometheus() == reg_b.to_prometheus()
    # state() is a copy: mutating it cannot corrupt the histogram
    counts, _c, _s = reg_b.histogram("net.check.seconds").state()
    counts[0] = 10 ** 9
    assert reg_a.to_prometheus() == reg_b.to_prometheus()


# ===================================================================
# bench schema: profile + slo sections
# ===================================================================


def _base_result():
    # a schema-valid base: the repo's own latest recorded round, with
    # any prior profile/slo sections stripped so tests attach their own
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_history.jsonl")
    rows = benchschema.load_history(path)
    base = dict(benchschema.extract_result(rows[-1]))
    base.pop("profile", None)
    base.pop("slo", None)
    assert benchschema.validate_result(base) == []
    return base


def test_validate_profile_section():
    good = {
        "hz": 47.0, "samples": 10,
        "host_legs": {"unmarshal": 0.1, "sig_verify": 0.0},
        "host_leg_coverage": 0.91,
        "stacks": {"commit-worker;a:b": 7},
        "dropped_stacks": 0,
    }
    assert benchschema.validate_profile(good) == []
    assert benchschema.validate_profile({"hz": 1.0}) != []  # missing keys
    bad = dict(good, host_legs={"unmarshal": -0.1})
    assert benchschema.validate_profile(bad) != []
    bad = dict(good, stacks={"s": 0})
    assert benchschema.validate_profile(bad) != []
    # a result carrying the section is gated through validate_result
    r = dict(_base_result(), profile=good)
    assert benchschema.validate_result(r) == []
    r = dict(_base_result(), profile={"hz": 1.0})
    assert benchschema.validate_result(r) != []


def test_validate_slo_section():
    row = {"objective": 0.99, "burn": 0.2, "budget_remaining": 0.8,
           "total": 100, "ok": True}
    good = {"window_s": 60.0, "slos": {"finality_p99": row}}
    assert benchschema.validate_slo(good) == []
    assert benchschema.validate_slo({"slos": {}}) != []  # no window
    bad = {"window_s": 60.0, "slos": {"x": {"burn": 0.2}}}
    problems = benchschema.validate_slo(bad)
    assert problems and "x" in problems[0]
    r = dict(_base_result(), slo=good)
    assert benchschema.validate_result(r) == []


def test_live_engine_output_is_schema_valid():
    engine = slo.reset(window_s=60.0)
    out = _drive(engine, finality_obs=[0.01] * 3, commit_obs=[0.01], enq=3)
    assert benchschema.validate_slo(out) == []


# ===================================================================
# ftstop compare --slo gate
# ===================================================================


def _history(tmp_path, results):
    p = tmp_path / "hist.jsonl"
    with open(p, "w") as fh:
        for r in results:
            fh.write(json.dumps(r) + "\n")
    return str(p)


def _args(history, no_fail=False):
    return argparse.Namespace(
        history=history, last=None, threshold=0.1, no_fail=no_fail,
    )


def _slo_section(ok):
    return {"window_s": 60.0, "slos": {
        "finality_p99": {"objective": 0.99, "good_frac": 1.0 if ok else 0.5,
                         "total": 10, "burn": 0.0 if ok else 50.0,
                         "budget_remaining": 1.0 if ok else 0.0,
                         "ok": ok, "target_s": 1.0},
    }}


def test_compare_slo_exit_codes(tmp_path, capsys):
    healthy = dict(_base_result(), slo=_slo_section(True))
    breached = dict(_base_result(), slo=_slo_section(False))
    assert ftstop.compare_slo(_args(_history(tmp_path, [healthy]))) == 0
    # the LATEST slo-carrying round decides
    assert ftstop.compare_slo(
        _args(_history(tmp_path, [healthy, breached]))
    ) == 1
    assert ftstop.compare_slo(
        _args(_history(tmp_path, [breached, healthy]))
    ) == 0
    assert ftstop.compare_slo(
        _args(_history(tmp_path, [healthy, breached]), no_fail=True)
    ) == 0
    # no slo-carrying rounds at all
    assert ftstop.compare_slo(_args(_history(tmp_path, [_base_result()]))) == 2
    out = capsys.readouterr()
    assert "BREACH" in out.out


def test_compare_slo_is_wired_into_main(tmp_path):
    healthy = dict(_base_result(), slo=_slo_section(True))
    rc = ftstop.main(
        ["compare", "--history", _history(tmp_path, [healthy]), "--slo"]
    )
    assert rc == 0
