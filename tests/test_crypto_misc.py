"""Tests: o2omp, nym signatures, identity signatures, audit checks."""
import pytest

from fabric_token_sdk_tpu.crypto import audit, hostmath as hm, nym, o2omp, pedersen, sign
from fabric_token_sdk_tpu.crypto.token import Metadata, Token


def test_o2omp_roundtrip(rng):
    ped = [hm.rand_g1(rng), hm.rand_g1(rng)]
    nbits = 3
    n = 1 << nbits
    index = 5
    r = hm.rand_zr(rng)
    commitments = [hm.rand_g1(rng) for _ in range(n)]
    commitments[index] = hm.g1_mul(ped[1], r)  # commitment to 0
    raw = o2omp.Prover(commitments, b"msg", ped, nbits, index, r, rng).prove()
    o2omp.Verifier(commitments, b"msg", ped, nbits).verify(raw)
    # different message binds -> reject
    with pytest.raises(ValueError):
        o2omp.Verifier(commitments, b"other", ped, nbits).verify(raw)
    # commitment list without a commitment to zero -> reject
    commitments[index] = hm.rand_g1(rng)
    with pytest.raises(ValueError):
        o2omp.Verifier(commitments, b"msg", ped, nbits).verify(raw)


def test_nym_signature(rng):
    params = [hm.rand_g1(rng), hm.rand_g1(rng)]
    sk = hm.rand_zr(rng)
    ny, bf = nym.new_nym(sk, params, rng)
    signer = nym.NymSigner(sk, bf, ny, params)
    raw = signer.sign(b"transfer-tx-1", rng)
    nym.NymVerifier(ny, params).verify(b"transfer-tx-1", raw)
    with pytest.raises(ValueError):
        nym.NymVerifier(ny, params).verify(b"transfer-tx-2", raw)
    other, _ = nym.new_nym(hm.rand_zr(rng), params, rng)
    with pytest.raises(ValueError):
        nym.NymVerifier(other, params).verify(b"transfer-tx-1", raw)


def test_identity_signature(rng):
    key = sign.keygen(rng)
    sig = key.sign(b"hello", rng)
    key.public.verify(b"hello", sig)
    pk2 = sign.PublicKey.from_bytes(key.public.to_bytes())
    pk2.verify(b"hello", sig)
    with pytest.raises(ValueError):
        key.public.verify(b"tampered", sig)


def test_auditor_check(rng):
    ped = [hm.rand_g1(rng) for _ in range(3)]
    bf = hm.rand_zr(rng)
    com = pedersen.token_commitment("USD", 9, bf, ped)
    t = Token(owner=b"alice", data=com)
    at = audit.auditable_token(t, b"alice-audit-info", "USD", 9, bf)
    key = sign.keygen(rng)
    auditor = audit.Auditor(ped, signer=key)
    auditor.check([at], [])
    sig = auditor.endorse(b"request", rng)
    key.public.verify(b"request", sig)
    bad = audit.auditable_token(t, b"", "USD", 8, bf)
    with pytest.raises(ValueError):
        auditor.check_token(bad)


def test_codec_hexlike_strings():
    """Token types that look like hex ints must survive the wire format."""
    from fabric_token_sdk_tpu.crypto.token import Metadata

    m = Metadata("0xBEEF", 5, 7)
    m2 = Metadata.from_bytes(m.to_bytes())
    assert m2.token_type == "0xBEEF" and isinstance(m2.token_type, str)


def test_malformed_proof_rejected_not_crash(rng):
    """Garbage bytes must raise ValueError, never TypeError/KeyError."""
    from fabric_token_sdk_tpu.crypto import o2omp, wellformedness as wf
    from fabric_token_sdk_tpu.crypto.serialization import dumps

    ped = [hm.rand_g1(rng), hm.rand_g1(rng)]
    v = o2omp.Verifier([hm.rand_g1(rng) for _ in range(4)], b"m", ped, 2)
    for garbage in [b"not json", dumps({"L": [5], "A": []}), dumps({"x": 1})]:
        with pytest.raises(ValueError):
            v.verify(garbage)
    tv = wf.TransferWFVerifier(ped + [hm.rand_g1(rng)], [hm.rand_g1(rng)], [hm.rand_g1(rng)])
    with pytest.raises(ValueError):
        tv.verify(b"\xff\xfe garbage")


def test_public_params_g2_subgroup_validation(rng):
    """Tampered params with wrong-subgroup G2 must fail validation."""
    from fabric_token_sdk_tpu.crypto.setup import setup

    pp = setup(base=2, exponent=1, rng=rng)
    pp.validate()
    # find an on-curve, non-subgroup twist point
    while True:
        x = (rng.randrange(hm.P), rng.randrange(hm.P))
        y = hm.fp2_sqrt(hm.fp2_add(hm.fp2_mul(hm.fp2_sqr(x), x), hm.B2))
        if y is not None and not hm.g2_in_subgroup((x, y)):
            pp.range_params.Q = (x, y)
            break
    with pytest.raises(ValueError):
        pp.validate()
