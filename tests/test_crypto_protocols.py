"""Protocol round trips + tamper rejection (host control plane)."""
import random
import pytest

from fabric_token_sdk_tpu.crypto import (
    elgamal,
    hostmath as hm,
    pedersen,
    pssign,
    rangeproof,
    sigproof,
    transfer,
    issue as issue_mod,
    token as tok,
    wellformedness as wf,
)
from fabric_token_sdk_tpu.crypto.setup import PublicParams, setup


@pytest.fixture(scope="module")
def pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))  # max value 15 — keeps pairings cheap


def test_setup_serialize_roundtrip(pp):
    raw = pp.serialize()
    pp2 = PublicParams.deserialize(raw)
    assert pp2.ped_params == pp.ped_params
    assert pp2.range_params.Q == pp.range_params.Q
    assert pp2.max_token_value() == 15
    pp2.validate()
    assert pp.compute_hash() == pp2.compute_hash()


def test_elgamal_roundtrip(rng):
    sk = elgamal.keygen(rng=rng)
    m = hm.rand_g1(rng)
    ct, _ = sk.pk.encrypt(m, rng)
    assert sk.decrypt(ct) == m


def test_pssign_roundtrip(rng):
    signer = pssign.keygen(2, rng)
    msgs = [5, 11]
    sig = signer.sign(msgs, rng)
    signer.verify(msgs, sig)
    rnd = signer.randomize(sig, rng)
    signer.verify(msgs, rnd)  # randomized sig still verifies
    with pytest.raises(ValueError):
        signer.verify([5, 12], sig)


def test_ps_blind_sign(rng):
    signer = pssign.keygen(2, rng)
    ped = [hm.rand_g1(rng) for _ in range(3)]  # 2 message bases + bf base
    msgs = [3, 9]
    bf = hm.rand_zr(rng)
    com = hm.g1_multiexp(ped, msgs + [bf])
    enc_sk = elgamal.keygen(rng=rng)
    verifier = pssign.VerifierWithHash(pk=signer.pk, Q=signer.Q)
    rec = pssign.Recipient(msgs, bf, com, enc_sk, ped, verifier, rng)
    req = rec.request()
    blind_signer = pssign.BlindSigner(signer, ped)
    resp = blind_signer.blind_sign(req)
    sig = rec.unblind(resp)  # verifies internally
    assert sig.R is not None and sig.S is not None
    # tampered request must be rejected
    req2 = rec.request()
    req2.proof.messages[0] = (req2.proof.messages[0] + 1) % hm.R
    with pytest.raises(ValueError):
        blind_signer.blind_sign(req2)


def test_membership_proof(rng, pp):
    rp = pp.range_params
    value = 3
    bf = hm.rand_zr(rng)
    com = hm.g1_multiexp(pp.ped_params[:2], [value, bf])
    w = sigproof.MembershipWitness(rp.signed_values[value], value, bf)
    proof = sigproof.MembershipProver(
        w, com, pp.ped_gen, rp.Q, rp.sign_pk, pp.ped_params[:2], rng
    ).prove()
    sigproof.MembershipVerifier(
        com, pp.ped_gen, rp.Q, rp.sign_pk, pp.ped_params[:2]
    ).verify(proof)
    # value NOT in the signed relationship with this commitment -> reject
    proof.value_resp = (proof.value_resp + 1) % hm.R
    with pytest.raises(ValueError):
        sigproof.MembershipVerifier(
            com, pp.ped_gen, rp.Q, rp.sign_pk, pp.ped_params[:2]
        ).verify(proof)


def test_range_proof(rng, pp):
    rp = pp.range_params
    tokens, wits = tok.tokens_with_witness([7, 14], "USD", pp.ped_params, rng)
    prover = rangeproof.RangeProver(
        [rangeproof.TokenWitness(w.token_type, w.value, w.bf) for w in wits],
        tokens, rp.signed_values, rp.base, rp.exponent,
        pp.ped_params, rp.sign_pk, pp.ped_gen, rp.Q, rng,
    )
    raw = prover.prove()
    rangeproof.RangeVerifier(
        tokens, rp.base, rp.exponent, pp.ped_params, rp.sign_pk, pp.ped_gen, rp.Q
    ).verify(raw)


def test_range_proof_out_of_range(rng, pp):
    rp = pp.range_params
    tokens, wits = tok.tokens_with_witness([16], "USD", pp.ped_params, rng)  # > 15
    with pytest.raises(ValueError):
        rangeproof.RangeProver(
            [rangeproof.TokenWitness(w.token_type, w.value, w.bf) for w in wits],
            tokens, rp.signed_values, rp.base, rp.exponent,
            pp.ped_params, rp.sign_pk, pp.ped_gen, rp.Q, rng,
        ).prove()


def test_transfer_wf(rng, pp):
    in_toks, in_w = tok.tokens_with_witness([5, 10], "USD", pp.ped_params, rng)
    out_toks, out_w = tok.tokens_with_witness([7, 8], "USD", pp.ped_params, rng)
    prover = wf.TransferWFProver(
        wf.TransferWFWitness(
            "USD",
            [w.value for w in in_w], [w.bf for w in in_w],
            [w.value for w in out_w], [w.bf for w in out_w],
        ),
        pp.ped_params, in_toks, out_toks, rng,
    )
    raw = prover.prove()
    wf.TransferWFVerifier(pp.ped_params, in_toks, out_toks).verify(raw)
    # unbalanced transfer must fail
    out_bad, out_bw = tok.tokens_with_witness([7, 9], "USD", pp.ped_params, rng)
    bad = wf.TransferWFProver(
        wf.TransferWFWitness(
            "USD",
            [w.value for w in in_w], [w.bf for w in in_w],
            [w.value for w in out_bw], [w.bf for w in out_bw],
        ),
        pp.ped_params, in_toks, out_bad, rng,
    ).prove()
    with pytest.raises(ValueError):
        wf.TransferWFVerifier(pp.ped_params, in_toks, out_bad).verify(bad)


def test_full_transfer_proof(rng, pp):
    in_toks, in_w = tok.tokens_with_witness([5, 10], "USD", pp.ped_params, rng)
    out_toks, out_w = tok.tokens_with_witness([12, 3], "USD", pp.ped_params, rng)
    raw = transfer.TransferProver(in_w, out_w, in_toks, out_toks, pp, rng).prove()
    transfer.TransferVerifier(in_toks, out_toks, pp).verify(raw)
    # swapped outputs -> stale proof must not verify
    with pytest.raises(ValueError):
        transfer.TransferVerifier(in_toks, list(reversed(out_toks)), pp).verify(raw)


def test_ownership_transfer_skips_range(rng, pp):
    in_toks, in_w = tok.tokens_with_witness([9], "USD", pp.ped_params, rng)
    out_toks, out_w = tok.tokens_with_witness([9], "USD", pp.ped_params, rng)
    raw = transfer.TransferProver(in_w, out_w, in_toks, out_toks, pp, rng).prove()
    assert transfer.TransferProof.from_bytes(raw).range_correctness is None
    transfer.TransferVerifier(in_toks, out_toks, pp).verify(raw)


@pytest.mark.parametrize("anonymous", [True, False])
def test_issue_proof(rng, pp, anonymous):
    tokens, wits = tok.tokens_with_witness([6, 9], "EUR", pp.ped_params, rng)
    raw = issue_mod.IssueProver(wits, tokens, anonymous, pp, rng).prove()
    issue_mod.IssueVerifier(tokens, anonymous, pp).verify(raw)
    # issue with a different type must not verify against these tokens
    tokens2, wits2 = tok.tokens_with_witness([6, 9], "USD", pp.ped_params, rng)
    with pytest.raises(ValueError):
        issue_mod.IssueVerifier(tokens2, anonymous, pp).verify(raw)


def test_token_in_the_clear(rng, pp):
    tokens, wits = tok.tokens_with_witness([5], "USD", pp.ped_params, rng)
    t = tok.Token(owner=b"alice", data=tokens[0])
    meta = tok.Metadata("USD", 5, wits[0].bf, owner=b"alice")
    assert tok.token_in_the_clear(t, meta, pp.ped_params) == ("USD", 5, b"alice")
    meta_bad = tok.Metadata("USD", 6, wits[0].bf)
    with pytest.raises(ValueError):
        tok.token_in_the_clear(t, meta_bad, pp.ped_params)
