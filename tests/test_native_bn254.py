"""Differential tests: native C BN254 host library vs the pure-Python twin.

Mirrors the reference's reliance on differential trust in its math backend
(mathlib pinned against gnark-crypto); here bn254.c must agree with
`crypto.hostmath`'s big-int definitions on every exported operation.
"""

import random

import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.native import bn254py as nb

pytestmark = pytest.mark.skipif(
    not nb.available(), reason="no C compiler / native build unavailable"
)

rng = random.Random(0xBEEF)


def _rand_pts(n):
    pts = [hm.g1_mul_py(hm.G1_GEN, rng.randrange(1, hm.R)) for _ in range(n)]
    pts[n // 2] = None  # include infinity
    return pts


def test_mul_batch_matches_python():
    pts = _rand_pts(8)
    ks = [rng.randrange(hm.R) for _ in range(8)]
    assert nb.g1_mul_batch(pts, ks) == [
        hm.g1_mul_py(p, k) for p, k in zip(pts, ks)
    ]


def test_mul_edge_scalars():
    g = hm.G1_GEN
    assert nb.g1_mul(g, 0) is None
    assert nb.g1_mul(g, hm.R) is None
    assert nb.g1_mul(g, 1) == g
    assert nb.g1_mul(g, hm.R - 1) == hm.g1_neg(g)
    assert nb.g1_mul(None, 123) is None
    # scalars are reduced mod R on the way in
    k = rng.randrange(hm.R)
    assert nb.g1_mul(g, k + hm.R) == hm.g1_mul_py(g, k)


def test_multiexp_and_sum_match_python():
    pts = _rand_pts(6)
    ks = [rng.randrange(hm.R) for _ in range(6)]
    assert nb.g1_multiexp(pts, ks) == hm.g1_multiexp_py(pts, ks)
    assert nb.g1_sum(pts) == hm.g1_sum_py(pts)
    assert nb.g1_multiexp([], []) is None


def test_multiexp_rows():
    rows_p = [_rand_pts(3) for _ in range(4)]
    rows_k = [[rng.randrange(hm.R) for _ in range(3)] for _ in range(4)]
    assert nb.g1_multiexp_rows(rows_p, rows_k) == [
        hm.g1_multiexp_py(p, k) for p, k in zip(rows_p, rows_k)
    ]


def test_hostmath_fast_path_installed():
    # In-process hostmath should have adopted the native path (unless the
    # env opted out), and its results must equal the pure twin's.
    k = rng.randrange(hm.R)
    assert hm.g1_mul(hm.G1_GEN, k) == hm.g1_mul_py(hm.G1_GEN, k)
    pts = _rand_pts(4)
    ks = [rng.randrange(hm.R) for _ in range(4)]
    assert hm.g1_multiexp(pts, ks) == hm.g1_multiexp_py(pts, ks)
    assert hm.g1_sum(pts) == hm.g1_sum_py(pts)
    assert hm.g1_mul_batch(pts, ks) == [hm.g1_mul_py(p, k) for p, k in zip(pts, ks)]


def test_g2_ops_match_python():
    ks = [rng.randrange(hm.R) for _ in range(3)]
    pts = [hm.g2_mul_py(hm.G2_GEN, k + 1) for k in ks] + [None]
    ks.append(7)
    assert nb.g2_mul_batch(pts, ks) == [
        hm.g2_mul_py(p, k) for p, k in zip(pts, ks)
    ]
    assert nb.g2_mul(hm.G2_GEN, 0) is None
    assert nb.g2_mul(hm.G2_GEN, 1) == hm.G2_GEN
    assert nb.g2_mul(hm.G2_GEN, hm.R - 1) == hm.g2_neg(hm.G2_GEN)
    assert nb.g2_multiexp(pts, ks) == hm.g2_multiexp_py(pts, ks)
    assert nb.g2_sum(pts) == hm.g2_sum_py(pts)


def test_pairing_matches_python():
    p = hm.g1_mul_py(hm.G1_GEN, 3)
    q = hm.g2_mul_py(hm.G2_GEN, 5)
    assert nb.pairing(p, q) == hm.pairing_py(p, q)


def test_pairing_bilinearity_and_product():
    p = hm.g1_mul_py(hm.G1_GEN, 11)
    q = hm.g2_mul_py(hm.G2_GEN, 13)
    a = rng.randrange(1, 1 << 30)
    assert nb.pairing(hm.g1_mul_py(p, a), q) == nb.pairing(p, hm.g2_mul_py(q, a))
    # e(P,Q) e(-P,Q) = 1 under the shared final exponentiation
    assert nb.pairing_product([(p, q), (hm.g1_neg(p), q)]) == hm.FP12_ONE
    # infinite legs contribute identity
    assert nb.pairing_product([(None, q), (p, None)]) == hm.FP12_ONE
    assert hm.gt_is_unity(nb.pairing_product([]))


def test_hostmath_pairing_fast_path():
    p = hm.g1_mul_py(hm.G1_GEN, 4)
    q = hm.g2_mul_py(hm.G2_GEN, 9)
    assert hm.pairing(p, q) == hm.pairing_py(p, q)
    assert hm.pairing(None, q) == hm.FP12_ONE
    assert hm.pairing_product([(p, q)]) == hm.pairing_product_py([(p, q)])
    k = rng.randrange(hm.R)
    assert hm.g2_mul(hm.G2_GEN, k) == hm.g2_mul_py(hm.G2_GEN, k)


def test_cancellation_inside_sum():
    # exercises the add -> inverse/doubling branches in C
    p = hm.g1_mul_py(hm.G1_GEN, 7)
    assert nb.g1_sum([p, hm.g1_neg(p)]) is None
    assert nb.g1_sum([p, p]) == hm.g1_mul_py(hm.G1_GEN, 14)
    assert nb.g1_multiexp([p, p], [5, hm.R - 5]) is None
