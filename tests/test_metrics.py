"""Metrics layer: concurrency, span nesting, export round-trips, the
disabled fast path, and crash-proof sidecar flushing (SIGTERM / deadline).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from fabric_token_sdk_tpu.utils import metrics as mx

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture
def registry():
    """Fresh scratch registry + enabled spans, global state restored."""
    was = mx.enabled()
    reg = mx.Registry()
    mx.enable(True)
    try:
        yield reg
    finally:
        mx.enable(was)


# ------------------------------------------------------------ concurrency


def test_concurrent_counter_and_histogram_updates(registry):
    c = registry.counter("t.count")
    h = registry.histogram("t.hist")
    g = registry.gauge("t.gauge")
    N, T = 2000, 8

    def work(k):
        for i in range(N):
            c.inc()
            h.observe(0.001 * (i % 7))
            g.set(k)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T
    snap = h.snapshot()
    assert snap["count"] == N * T
    assert sum(snap["buckets"].values()) == N * T
    assert 0 <= g.value < T


def test_counter_get_or_create_races(registry):
    """Same-name instrument from many threads resolves to ONE counter."""
    seen = []

    def work():
        c = registry.counter("shared")
        c.inc()
        seen.append(c)

    threads = [threading.Thread(target=work) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert registry.counter("shared").value == 16
    assert all(c is seen[0] for c in seen)


# ------------------------------------------------------------ span trees


def test_span_nesting_builds_tree():
    was = mx.enabled()
    mx.enable(True)
    before = len(mx.REGISTRY.snapshot()["spans"])
    try:
        with mx.span("outer", who="test") as outer:
            with mx.span("inner.a"):
                with mx.span("leaf"):
                    pass
            with mx.span("inner.b"):
                pass
    finally:
        mx.enable(was)
    assert outer.end is not None
    assert [c.name for c in outer.children] == ["inner.a", "inner.b"]
    assert [c.name for c in outer.children[0].children] == ["leaf"]
    # only the ROOT is recorded in the registry; children hang off it
    spans = mx.REGISTRY.snapshot()["spans"]
    assert len(spans) == before + 1
    agg = mx.REGISTRY.span_summary()
    for name in ("outer", "inner.a", "inner.b", "leaf"):
        assert agg[name]["count"] >= 1
    # span durations auto-feed the <name>.seconds histogram
    assert mx.REGISTRY.histogram("outer.seconds").count >= 1


def test_span_duration_accumulates_child_time():
    was = mx.enabled()
    mx.enable(True)
    try:
        with mx.span("parent.timed") as p:
            with mx.span("child.timed"):
                time.sleep(0.02)
    finally:
        mx.enable(was)
    assert p.duration >= 0.02
    assert p.children[0].duration >= 0.02


# ------------------------------------------------------------ export


def test_json_export_round_trip(registry):
    registry.counter("a.count").inc(5)
    registry.gauge("b.gauge").set(2.5)
    h = registry.histogram("c.seconds")
    for v in (0.002, 0.3, 7.0, 700.0):
        h.observe(v)
    registry.set_meta("platform", "cpu")
    registry.record_phase("compile", 100.0, 134.5, program="miller_tile")

    d = json.loads(registry.to_json())
    assert d["counters"]["a.count"] == 5
    assert d["gauges"]["b.gauge"] == 2.5
    hh = d["histograms"]["c.seconds"]
    assert hh["count"] == 4
    assert abs(hh["sum"] - 707.302) < 1e-6
    assert hh["buckets"]["+Inf"] == 1  # 700 > top bucket
    assert d["meta"]["platform"] == "cpu"
    assert d["phases"][0]["name"] == "compile"
    assert d["phases"][0]["elapsed_s"] == 34.5
    assert d["phases"][0]["attrs"]["program"] == "miller_tile"


def test_prometheus_export(registry):
    registry.counter("jax.cache.load_failures").inc(3)
    registry.gauge("vault.tokens.held").set(12)
    h = registry.histogram("verify.seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    text = registry.to_prometheus()
    assert "# TYPE fts_jax_cache_load_failures counter" in text
    assert "fts_jax_cache_load_failures 3" in text
    assert "fts_vault_tokens_held 12" in text
    # cumulative buckets: 0.1 -> 1, 1.0 -> 2, +Inf -> 3
    assert 'fts_verify_seconds_bucket{le="0.1"} 1' in text
    assert 'fts_verify_seconds_bucket{le="1"} 2' in text
    assert 'fts_verify_seconds_bucket{le="+Inf"} 3' in text
    assert "fts_verify_seconds_count 3" in text


def test_ftsmetrics_cli_show_and_diff(registry, tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftsmetrics
    finally:
        sys.path.pop(0)
    registry.counter("network.tx.valid").inc(7)
    registry.record_phase("setup", 0.0, 1.25)
    h = registry.histogram("compile.seconds", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(30.0)
    a = tmp_path / "a.metrics.json"
    a.write_text(registry.to_json())
    registry.counter("network.tx.valid").inc(3)
    b = tmp_path / "b.metrics.json"
    b.write_text(registry.to_json())

    ftsmetrics.show(str(a))
    out = capsys.readouterr().out
    assert "network.tx.valid" in out and "setup" in out
    ftsmetrics.diff(str(a), str(b))
    out = capsys.readouterr().out
    assert "7 -> 10" in out
    # Prometheus view must include the histogram series from the sidecar
    ftsmetrics.show(str(a), prometheus=True)
    out = capsys.readouterr().out
    assert 'fts_compile_seconds_bucket{le="1"} 1' in out
    assert 'fts_compile_seconds_bucket{le="+Inf"} 2' in out
    assert "fts_compile_seconds_count 2" in out


# ------------------------------------------------------------ disabled path


def test_disabled_span_records_nothing_and_is_cheap():
    was = mx.enabled()
    mx.enable(False)
    try:
        before = len(mx.REGISTRY.snapshot()["spans"])
        t0 = time.monotonic()
        for _ in range(20000):
            with mx.span("hot.loop", k=1):
                pass
        elapsed = time.monotonic() - t0
        assert len(mx.REGISTRY.snapshot()["spans"]) == before
        assert mx.REGISTRY.histogram("hot.loop.seconds").count == 0
        # smoke bound, not a benchmark: 20k disabled spans in well under
        # the time one single pairing takes
        assert elapsed < 2.0
    finally:
        mx.enable(was)


def test_tracer_facade_feeds_shared_registry():
    from fabric_token_sdk_tpu.utils.tracing import tracer

    was = mx.enabled()
    mx.enable(True)
    try:
        tracer.count("facade.count", 4)
        with tracer.span("facade.span"):
            pass
    finally:
        mx.enable(was)
    assert mx.REGISTRY.counter("facade.count").value >= 4
    assert mx.REGISTRY.span_summary()["facade.span"]["count"] >= 1


def test_service_plane_counters_populate():
    """Acceptance: one end-to-end fungible flow must land metrics from
    at least three services (selector, vault, ttx) plus the network."""
    from fabric_token_sdk_tpu.drivers.fabtoken import (
        FabTokenDriver,
        FabTokenPublicParams,
    )
    from fabric_token_sdk_tpu.services.ttx import Transaction
    from test_services_fungible import build_env

    was = mx.enabled()
    mx.enable(True)
    base = {
        name: mx.REGISTRY.counter(name).value
        for name in (
            "selector.lock.acquired",
            "vault.tokens.stored",
            "vault.tokens.spent",
            "ttx.submitted",
            "ttx.committed",
            "network.tx.valid",
        )
    }
    try:
        network, auditor_svc, parties, issuer, alice, bob = build_env(
            lambda: FabTokenDriver(FabTokenPublicParams())
        )
        tx = Transaction(parties["issuer-node"], "mx-issue")
        tx.issue("issuer", "USD", [10], [alice.recipient_identity()],
                 anonymous=False)
        tx.collect_endorsements(auditor_svc)
        tx.submit()
        tx2 = Transaction(parties["alice-node"], "mx-pay")
        tx2.transfer("alice", "USD", [4], [bob.recipient_identity()])
        tx2.collect_endorsements(auditor_svc)
        tx2.submit()
    finally:
        mx.enable(was)

    def delta(name):
        return mx.REGISTRY.counter(name).value - base[name]

    assert delta("selector.lock.acquired") >= 1
    assert delta("vault.tokens.stored") >= 2  # issue output + transfer outs
    assert delta("vault.tokens.spent") >= 1
    assert delta("ttx.submitted") == 2
    assert delta("ttx.committed") == 2
    assert delta("network.tx.valid") == 2
    # span histograms captured the stage durations
    for h in ("ttx.assemble.seconds", "ttx.endorse.seconds",
              "ttx.order_and_finality.seconds", "network.submit.seconds",
              "vault.on_finality.seconds", "selector.select.seconds"):
        assert mx.REGISTRY.histogram(h).count >= 1, f"missing {h}"


def test_native_selfcheck_counted():
    """hostmath's import-time self-check must land in the registry
    (pass on this box where the .so builds, or an explanatory fail)."""
    from fabric_token_sdk_tpu.crypto import hostmath as hm

    passed = mx.REGISTRY.counter("native.selfcheck.pass").value
    failed = mx.REGISTRY.counter("native.selfcheck.fail").value
    if hm.NATIVE_G1:
        assert passed >= 1
        assert failed == 0
    else:
        # native disabled/unbuildable is fine — but a counted PASS with
        # native not installed would mean it was silently dropped after
        # adoption
        assert passed == 0


# ------------------------------------------------------------ sidecar


def test_flush_sidecar_atomic(tmp_path, registry):
    path = tmp_path / "t.metrics.json"
    mx.REGISTRY.counter("flush.check").inc()
    out = mx.flush_sidecar(str(path))
    assert out == str(path)
    d = json.loads(path.read_text())
    assert d["counters"]["flush.check"] >= 1
    assert not list(tmp_path.glob("*.tmp"))


def _spawn_bench(tmp_path, extra_env):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["_FTS_BENCH_REEXEC"] = "1"  # never re-exec away from CPU
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if ".axon_site" not in p
    )
    env["FTS_METRICS_SIDECAR"] = str(tmp_path / "BENCH_test.metrics.json")
    env["FTS_HEARTBEAT_SECS"] = "1"
    env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py")],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    return proc, env["FTS_METRICS_SIDECAR"]


def _wait_for_heartbeat(proc, timeout=180.0):
    """Read stderr lines until the first phase-stamped heartbeat."""
    lines = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stderr.readline()
        if not line:
            if proc.poll() is not None:
                break
            time.sleep(0.05)
            continue
        lines.append(line)
        if "phase=" in line:
            return lines
    raise AssertionError(f"no heartbeat before timeout; stderr: {lines!r}")


def _drain(proc):
    try:
        proc.stdout.read()
        proc.stderr.read()
    except Exception:
        pass


def test_bench_sidecar_flushed_on_sigterm(tmp_path):
    """A SIGTERM'd bench run (what `timeout` sends first) must leave a
    phase-stamped metrics sidecar — rc=124 is not a zero-info outcome."""
    proc, sidecar = _spawn_bench(tmp_path, {})
    try:
        _wait_for_heartbeat(proc)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
        _drain(proc)
    assert os.path.exists(sidecar), "SIGTERM did not flush the sidecar"
    d = json.loads(open(sidecar).read())
    assert d["meta"]["entry"] == "bench.py"
    assert d["meta"]["killed_by_signal"] == int(signal.SIGTERM)
    assert d["phases"], "no phase timeline recorded"
    assert "counters" in d and "histograms" in d
    # the flight recorder shipped its ring alongside the metrics sidecar:
    # the lifecycle trail (phase events at minimum) survives the kill
    flight = sidecar[: -len(".metrics.json")] + ".flight.json"
    assert os.path.exists(flight), "SIGTERM did not dump the flight ring"
    fd = json.loads(open(flight).read())
    assert fd["events"], "flight ring dumped empty"
    assert any(e["kind"] == "phase" for e in fd["events"])
    # exit status must still reflect the kill (handler chains to default)
    assert proc.returncode != 0


def test_bench_sidecar_flushed_on_deadline(tmp_path):
    """Simulated timeout via a short FTS_BENCH_DEADLINE: the watchdog
    must log to stderr, flush the sidecar with per-phase wall times and
    compile/cache counters, and print a DEGRADED-but-parsed result JSON
    (exit 0) instead of dying as a silent rc=124."""
    proc, sidecar = _spawn_bench(tmp_path, {"FTS_BENCH_DEADLINE": "8"})
    try:
        proc.wait(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
        out, err = proc.communicate(timeout=30)
    assert proc.returncode == 0, f"expected rc=0 with degraded JSON, got {proc.returncode}; stderr tail: {err[-2000:]}"
    assert "DEADLINE" in err
    # the driver can parse the outcome: degraded JSON with the live phase
    degraded = json.loads(out.strip().splitlines()[-1])
    assert degraded["degraded"] is True
    assert degraded["metric"] == "zkatdlog_transfer_verify_throughput"
    assert degraded["deadline_s"] == 8.0
    assert "phase" in degraded
    assert os.path.exists(sidecar), "deadline did not flush the sidecar"
    d = json.loads(open(sidecar).read())
    assert d["meta"]["deadline_fired_s"] == 8.0
    # the phase timeline pinpoints where the time went at death
    phases = {p["name"] for p in d["phases"]}
    assert "init" in phases
    assert any("elapsed_s" in p for p in d["phases"])
    assert "progress.phase" in d["meta"]  # the phase that was live at kill
    # compile/cache counters exist in the dump (may be zero this early)
    assert isinstance(d["counters"], dict)
    # ISSUE acceptance: a deadline-killed bench leaves a flight-record
    # sidecar whose ring ends with the watchdog's own death marker, after
    # the lifecycle events (phases at minimum) that led up to it
    flight = sidecar[: -len(".metrics.json")] + ".flight.json"
    assert os.path.exists(flight), "deadline did not dump the flight ring"
    fd = json.loads(open(flight).read())
    kinds = [e["kind"] for e in fd["events"]]
    assert "phase" in kinds
    assert "bench.deadline" in kinds
    dl = [e for e in fd["events"] if e["kind"] == "bench.deadline"][-1]
    assert dl["deadline_s"] == 8.0 and "phase" in dl
