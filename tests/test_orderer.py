"""Orderer subsystem: multi-tx blocks, batched block validation, MVCC.

Covers the block pipeline end to end: intra-block double spends (the
LATER tx is invalidated, never the block), conflicts across consecutive
blocks, same-shape zkatdlog groups riding ONE `BatchedTransferVerifier`
call, mixed batched/host blocks (issues + odd shapes fall back to the
host `RequestValidator`), differential block-mode vs per-tx commits,
listener crash isolation, block-cut policy, and snapshot/restore of
multi-tx blocks.

The zkatdlog cases use 1-in/1-out transfers on purpose: that shape skips
range proofs (reference transfer.go:55-59), so the batched path touches
only the non-slow stage tiles — the pairing-heavy shapes stay in the
slow-marked tests.
"""
import random
import threading

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network, TxStatus
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import metrics as mx


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def build_env(driver_factory, policy=None):
    """issuer + alice + bob on one network, no auditor (these tests
    target the ordering/commit plane, not the audit plane)."""
    network = Network(RequestValidator(driver_factory()), policy=policy)
    parties = {
        name: Party(name, driver_factory(), network)
        for name in ("issuer-node", "alice-node", "bob-node")
    }
    issuer = parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet("alice", anonymous=False)
    bob = parties["bob-node"].new_owner_wallet("bob", anonymous=False)
    vdrv = network.validator.driver
    if hasattr(vdrv, "pp") and hasattr(vdrv.pp, "add_issuer"):
        vdrv.pp.add_issuer(issuer.identity)
    return network, parties, issuer, alice, bob


def fab_env(policy=None):
    pp = FabTokenPublicParams()
    return build_env(lambda: FabTokenDriver(pp), policy)


def zk_env(zk_pp, policy=None):
    return build_env(lambda: ZKATDLogDriver(zk_pp), policy)


def issue_to(parties, alice, values, anchor):
    """One committed issue tx putting `values` USD tokens in alice's vault."""
    tx = Transaction(parties["issuer-node"], anchor)
    tx.issue(
        "issuer", "USD", list(values),
        [alice.recipient_identity()] * len(values), anonymous=False,
    )
    tx.collect_endorsements(None)
    tx.submit()
    return tx


def manual_transfer(party, token_id, value, recipient, anchor):
    """Assemble + sign a transfer spending ONE specific token, bypassing
    the selector (whose locks would forbid crafting a double spend)."""
    req = party.tms.new_request(anchor)
    tokens, metas = party.vault.get_many([token_id])
    party.tms.add_transfer(req, [token_id], tokens, metas, "USD", [value], [recipient])
    party.tms.sign_transfers(req)
    return req


def _counter(name):
    return mx.REGISTRY.counter(name).value


# ===================================================================
# MVCC inside and across blocks (host plane, fabtoken)
# ===================================================================


def test_intra_block_double_spend_invalidates_later_tx():
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=8))
    alice_p, bob_p = parties["alice-node"], parties["bob-node"]
    issue_to(parties, alice, [5], "seed")
    tid = alice_p.vault.token_ids()[0]
    req_a = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), "spend-a")
    req_b = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), "spend-b")

    h0 = network.height()
    events = network.submit_many([req_a.to_bytes(), req_b.to_bytes()])
    assert events[0].status == TxStatus.VALID
    assert events[1].status == TxStatus.INVALID
    assert "already spent" in events[1].message
    # ONE block carried both txs; only the conflicting one was dropped
    assert network.height() == h0 + 1
    assert network.block(h0).txs == ["spend-a", "spend-b"]
    assert bob_p.balance("USD") == 5
    assert alice_p.balance("USD") == 0
    # finality events are queryable per tx
    assert network.status("spend-a").status == TxStatus.VALID
    assert network.status("spend-b").status == TxStatus.INVALID


def test_conflict_across_consecutive_blocks():
    network, parties, issuer, alice, bob = fab_env()
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [7], "seed")
    tid = alice_p.vault.token_ids()[0]
    req_a = manual_transfer(alice_p, tid, 7, bob.recipient_identity(), "blk-a")
    req_b = manual_transfer(alice_p, tid, 7, bob.recipient_identity(), "blk-b")

    h0 = network.height()
    ev_a = network.submit(req_a.to_bytes())
    ev_b = network.submit(req_b.to_bytes())  # next block, same input
    assert ev_a.status == TxStatus.VALID
    assert ev_b.status == TxStatus.INVALID and "already spent" in ev_b.message
    assert network.height() == h0 + 2
    # idempotent resubmission returns the recorded event, adds no block
    assert network.submit(req_a.to_bytes()).status == TxStatus.VALID
    assert network.height() == h0 + 2


def test_intra_block_create_then_spend():
    """An output created by an EARLIER tx in the block is spendable by a
    later tx of the same block (the MVCC overlay sees block-local
    writes)."""
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=4))
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [9], "seed")
    tid = alice_p.vault.token_ids()[0]
    req_a = manual_transfer(alice_p, tid, 9, alice.recipient_identity(), "hop-1")
    # hop-2 spends hop-1's output, which exists only inside the block
    from fabric_token_sdk_tpu.models.token import ID

    hop1_out = ID("hop-1", 0)
    req_b = alice_p.tms.new_request("hop-2")
    # the output bytes of hop-1 are what its action wrote; for fabtoken
    # metadata mirrors the output, so assemble from the action outcome
    from fabric_token_sdk_tpu.crypto.serialization import loads

    out_raw = loads(req_a.transfers[0].action)["outputs"][0]
    alice_p.tms.add_transfer(
        req_b, [hop1_out], [out_raw], [out_raw], "USD", [9],
        [bob.recipient_identity()],
    )
    alice_p.tms.sign_transfers(req_b)

    events = network.submit_many([req_a.to_bytes(), req_b.to_bytes()])
    assert [e.status for e in events] == [TxStatus.VALID, TxStatus.VALID]
    assert parties["bob-node"].balance("USD") == 9


def test_differential_block_vs_per_tx():
    """A block commit and per-tx commits of the SAME requests agree on
    every status and on the final ledger state."""
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=8))
    alice_p = parties["alice-node"]
    seed_tx = issue_to(parties, alice, [4, 6], "seed")
    ids = alice_p.vault.token_ids()
    req_a = manual_transfer(alice_p, ids[0], 4, bob.recipient_identity(), "d-a")
    req_b = manual_transfer(alice_p, ids[0], 4, bob.recipient_identity(), "d-b")
    req_c = manual_transfer(alice_p, ids[1], 6, bob.recipient_identity(), "d-c")
    batch = [req_a.to_bytes(), req_b.to_bytes(), req_c.to_bytes()]
    block_events = network.submit_many(batch)

    # fresh ledger, same public params, one tx per block, no device plane
    vdrv = network.validator.driver
    net2 = Network(
        RequestValidator(FabTokenDriver(vdrv.pp)),
        policy=BlockPolicy(max_block_txs=1, use_batched=False),
    )
    seq_events = [net2.submit(rb) for rb in [seed_tx.request.to_bytes()] + batch]
    assert [e.status for e in seq_events[1:]] == [e.status for e in block_events]
    from fabric_token_sdk_tpu.models.token import ID

    for anchor, n_out in (("d-a", 1), ("d-c", 1)):
        for i in range(n_out):
            assert network.exists(ID(anchor, i)) == net2.exists(ID(anchor, i))
    assert not net2.exists(ID("d-b", 0)) and not network.exists(ID("d-b", 0))


# ===================================================================
# Batched zkatdlog block validation (device plane, 1-in/1-out shapes)
# ===================================================================


def test_zk_block_of_8_rides_batched_verifier(zk_pp):
    """Acceptance: a block of >= 8 same-shape zkatdlog transfers
    validates through ONE BatchedTransferVerifier call (asserted via the
    batch.* and ledger.block.* metrics) with per-tx finality."""
    network, parties, issuer, alice, bob = zk_env(
        zk_pp, BlockPolicy(max_block_txs=16, min_batch=2)
    )
    alice_p, bob_p = parties["alice-node"], parties["bob-node"]
    issue_to(parties, alice, [5] * 8, "seed-8")

    txs = []
    for i in range(8):
        t = Transaction(alice_p, f"pay-{i}")
        t.transfer("alice", "USD", [5], [bob.recipient_identity()])  # (1,1)
        t.collect_endorsements(None)
        txs.append(t)

    before_bt = _counter("batch.transfer.txs")
    before_batched = _counter("ledger.validate.batched")
    before_host = _counter("ledger.validate.host")
    blocks_before = _counter("ledger.blocks.committed")
    size_hist = mx.REGISTRY.histogram("ledger.block.size")
    size_count_before = size_hist.count
    h0 = network.height()

    for t in txs:
        t.submit_async()  # ttx ordering stage: enqueue without waiting
    network.flush()  # cut ONE deterministic 8-tx block
    events = [t.wait() for t in txs]

    assert all(e.status == TxStatus.VALID for e in events)
    assert network.height() == h0 + 1
    assert network.block(h0).txs == [f"pay-{i}" for i in range(8)]
    # all 8 proofs went through the batched device plane, none through host
    assert _counter("batch.transfer.txs") - before_bt == 8
    assert _counter("ledger.validate.batched") - before_batched == 8
    assert _counter("ledger.validate.host") - before_host == 0
    assert _counter("ledger.blocks.committed") - blocks_before == 1
    assert size_hist.count == size_count_before + 1
    assert bob_p.balance("USD") == 40
    assert alice_p.balance("USD") == 0


def test_zk_block_differential_vs_host(zk_pp):
    """Batched block commit and per-tx host commits of the SAME zkatdlog
    requests agree on every status (including the MVCC conflict)."""
    network, parties, issuer, alice, bob = zk_env(
        zk_pp, BlockPolicy(max_block_txs=8, min_batch=2)
    )
    alice_p = parties["alice-node"]
    seed = issue_to(parties, alice, [5, 5], "zk-seed")
    ids = alice_p.vault.token_ids()
    req_a = manual_transfer(alice_p, ids[0], 5, bob.recipient_identity(), "zk-a")
    req_b = manual_transfer(alice_p, ids[1], 5, bob.recipient_identity(), "zk-b")
    req_c = manual_transfer(alice_p, ids[0], 5, bob.recipient_identity(), "zk-c")
    batch = [req_a.to_bytes(), req_b.to_bytes(), req_c.to_bytes()]

    before_bt = _counter("batch.transfer.txs")
    block_events = network.submit_many(batch)
    # all three same-shape proofs batch-verified; the conflict is MVCC's
    assert _counter("batch.transfer.txs") - before_bt == 3
    assert [e.status for e in block_events] == [
        TxStatus.VALID, TxStatus.VALID, TxStatus.INVALID,
    ]
    assert "already spent" in block_events[2].message

    net2 = Network(
        RequestValidator(ZKATDLogDriver(zk_pp)),
        policy=BlockPolicy(max_block_txs=1, use_batched=False),
    )
    seq = [net2.submit(rb) for rb in [seed.request.to_bytes()] + batch]
    assert [e.status for e in seq[1:]] == [e.status for e in block_events]


def test_zk_mixed_block_host_and_batched(zk_pp):
    """One block mixing every plane: an issue (host), a same-shape
    transfer group (batched), and an odd-shape singleton transfer (host
    fallback) — plus an issue-only block as the empty-group case."""
    network, parties, issuer, alice, bob = zk_env(
        zk_pp, BlockPolicy(max_block_txs=8, min_batch=2)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5, 5, 5], "mx-seed")  # issue-only block: no groups

    t1 = Transaction(alice_p, "mx-t1")
    t1.transfer("alice", "USD", [5], [bob.recipient_identity()])  # (1,1)
    t1.collect_endorsements(None)
    t2 = Transaction(alice_p, "mx-t2")
    t2.transfer("alice", "USD", [5], [bob.recipient_identity()])  # (1,1)
    t2.collect_endorsements(None)
    t3 = Transaction(alice_p, "mx-t3")
    t3.transfer("alice", "USD", [3], [bob.recipient_identity()])  # (1,2): change
    t3.collect_endorsements(None)
    issue2 = Transaction(parties["issuer-node"], "mx-issue2")
    issue2.issue("issuer", "USD", [2], [alice.recipient_identity()],
                 anonymous=False)
    issue2.collect_endorsements(None)

    before_batched = _counter("ledger.validate.batched")
    before_host = _counter("ledger.validate.host")
    h0 = network.height()
    events = network.submit_many(
        [issue2.request.to_bytes(), t1.request.to_bytes(),
         t2.request.to_bytes(), t3.request.to_bytes()]
    )
    assert all(e.status == TxStatus.VALID for e in events)
    assert network.height() == h0 + 1
    # the (1,1) pair was batched; the (1,2) singleton fell back to host
    assert _counter("ledger.validate.batched") - before_batched == 2
    assert _counter("ledger.validate.host") - before_host == 1
    assert parties["bob-node"].balance("USD") == 13
    assert alice_p.balance("USD") == 4  # 2 change + 2 fresh issue


def test_zk_block_through_sharded_pipeline(zk_pp):
    """Virtual-device smoke (satellite acceptance): one batched zk block
    commits through the mesh-sharded pipeline — `Network(mesh=...)` on
    the 8-virtual-device plane routes every same-shape group's
    stage-tile composition through the dp x mp per-shard dispatch, with
    identical verdicts and per-tx finality."""
    import jax

    from fabric_token_sdk_tpu.parallel import MeshConfig

    assert len(jax.devices()) == 8  # ensure_virtual_devices(8) in conftest
    pp = zk_pp
    network = Network(
        RequestValidator(ZKATDLogDriver(pp)),
        policy=BlockPolicy(max_block_txs=8, min_batch=2),
        mesh=MeshConfig.build(8, 2),
    )
    parties = {
        name: Party(name, ZKATDLogDriver(pp), network)
        for name in ("issuer-node", "alice-node", "bob-node")
    }
    issuer = parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet("alice", anonymous=False)
    bob = parties["bob-node"].new_owner_wallet("bob", anonymous=False)
    pp.add_issuer(issuer.identity)
    issue_to(parties, alice, [5] * 4, "sh-seed")

    txs = []
    for i in range(4):
        t = Transaction(parties["alice-node"], f"sh-{i}")
        t.transfer("alice", "USD", [5], [bob.recipient_identity()])  # (1,1)
        t.collect_endorsements(None)
        txs.append(t)

    before_bt = _counter("batch.transfer.txs")
    before_sharded = _counter("stages.sharded_calls")
    for t in txs:
        t.submit_async()
    network.flush()
    events = [t.wait() for t in txs]
    assert all(e.status == TxStatus.VALID for e in events)
    # all 4 proofs rode ONE batched call, and the call rode the mesh
    assert _counter("batch.transfer.txs") - before_bt == 4
    assert _counter("stages.sharded_calls") > before_sharded
    assert parties["bob-node"].balance("USD") == 20


def test_zk_batched_group_rejects_tampered_proof(zk_pp):
    """A tampered proof inside a batched group must invalidate ONLY its
    own tx: the device verdict (False) reaches the driver as a
    ValidationError while the group's other txs commit."""
    network, parties, issuer, alice, bob = zk_env(
        zk_pp, BlockPolicy(max_block_txs=8, min_batch=2)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5, 5], "tamper-seed")
    ids = alice_p.vault.token_ids()
    req_ok = manual_transfer(alice_p, ids[0], 5, bob.recipient_identity(), "tp-ok")
    req_bad = manual_transfer(alice_p, ids[1], 5, bob.recipient_identity(), "tp-bad")
    # corrupt the wf proof inside the action, then re-sign the tampered
    # request so only the PROOF is at fault
    from fabric_token_sdk_tpu.crypto.serialization import dumps, loads
    from fabric_token_sdk_tpu.crypto.transfer import TransferProof
    from fabric_token_sdk_tpu.crypto.wellformedness import TransferWF
    from fabric_token_sdk_tpu.crypto import hostmath as hm

    action = loads(req_bad.transfers[0].action)
    proof = TransferProof.from_bytes(action["proof"])
    wf = TransferWF.from_bytes(proof.wf)
    wf.sum_resp = (wf.sum_resp + 1) % hm.R
    proof.wf = wf.to_bytes()
    action["proof"] = proof.to_bytes()
    req_bad.transfers[0].action = dumps(action)
    alice_p.tms.sign_transfers(req_bad)

    before_bt = _counter("batch.transfer.txs")
    events = network.submit_many([req_ok.to_bytes(), req_bad.to_bytes()])
    assert _counter("batch.transfer.txs") - before_bt == 2  # both batched
    assert events[0].status == TxStatus.VALID
    assert events[1].status == TxStatus.INVALID
    assert "invalid transfer proof" in events[1].message
    # the untampered token is spent, the tampered one is not
    assert network.exists(ids[1]) and not network.exists(ids[0])


# ===================================================================
# Commit-loop robustness + policy + persistence
# ===================================================================


def test_transient_internal_error_is_not_cached():
    """A non-ValidationError fault (flaky native call, OOM) fails the
    ATTEMPT but is never recorded as a durable rejection — an identical
    resubmission can succeed once the fault clears."""
    network, parties, issuer, alice, bob = fab_env()
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5], "seed")
    tid = alice_p.vault.token_ids()[0]
    req = manual_transfer(alice_p, tid, 5, bob.recipient_identity(), "flaky")

    orig = network.validator.validate
    calls = {"n": 0}

    def flaky(*args, **kwargs):
        if calls["n"] == 0:
            calls["n"] += 1
            raise MemoryError("transient fault")
        return orig(*args, **kwargs)

    network.validator.validate = flaky
    ev1 = network.submit(req.to_bytes())
    assert ev1.status == TxStatus.INVALID
    assert "internal validation error" in ev1.message
    assert network.status("flaky") is None  # nothing durable recorded
    ev2 = network.submit(req.to_bytes())  # identical resubmission
    assert ev2.status == TxStatus.VALID
    assert parties["bob-node"].balance("USD") == 5


def test_listener_exception_does_not_abort_commit():
    network, parties, issuer, alice, bob = fab_env()
    seen = []

    def boom(event, request):
        raise RuntimeError("listener crashed")

    network.subscribe(boom)
    network.subscribe(lambda e, r: seen.append(e.tx_id))
    before = _counter("ledger.listener.errors")
    issue_to(parties, alice, [5], "seed")  # would raise before the fix
    assert _counter("ledger.listener.errors") - before >= 1
    assert "seed" in seen  # listeners AFTER the crasher still ran
    assert parties["alice-node"].balance("USD") == 5  # commit completed


def test_block_cut_policy_and_snapshot_restore():
    network, parties, issuer, alice, bob = fab_env(BlockPolicy(max_block_txs=2))
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [1, 2, 3, 4, 5], "seed")
    reqs = [
        manual_transfer(alice_p, tid, v, bob.recipient_identity(), f"cut-{v}")
        for v, tid in zip([1, 2, 3, 4, 5], alice_p.vault.token_ids())
    ]
    h0 = network.height()
    events = network.submit_many([r.to_bytes() for r in reqs])
    assert all(e.status == TxStatus.VALID for e in events)
    assert network.height() == h0 + 3  # 2 + 2 + 1
    assert [len(network.block(h0 + i).txs) for i in range(3)] == [2, 2, 1]

    snap = network.snapshot()
    net2 = Network.restore(
        RequestValidator(FabTokenDriver(network.validator.driver.pp)), snap
    )
    assert net2.height() == network.height()
    assert net2.block(h0).txs == network.block(h0).txs
    assert net2.status("cut-3").status == TxStatus.VALID


def test_concurrent_submitters_group_commit():
    """Concurrent submitters race for the commit lock; every tx lands in
    exactly one block and all commit."""
    network, parties, issuer, alice, bob = fab_env()
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [2, 2, 2, 2], "seed")
    reqs = [
        manual_transfer(alice_p, tid, 2, bob.recipient_identity(), f"par-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    h0 = network.height()
    results = []
    barrier = threading.Barrier(len(reqs))

    def worker(rb):
        barrier.wait()
        results.append(network.submit(rb))

    threads = [threading.Thread(target=worker, args=(r.to_bytes(),)) for r in reqs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(e.status == TxStatus.VALID for e in results)
    committed = [tx for i in range(h0, network.height())
                 for tx in network.block(i).txs]
    assert sorted(committed) == sorted(f"par-{i}" for i in range(len(reqs)))
    assert parties["bob-node"].balance("USD") == 8
