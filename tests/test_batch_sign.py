"""Batched signature plane: differential identity with the host path.

The `BatchedSchnorrVerifier` (crypto/batch_sign.py) and the block
pipeline's obligation collection (`BlockValidationPipeline.sign_verdicts`)
can only ACCELERATE signature checking, never change accept/reject —
these tests pin that contract: batched vs host verdict identity over
mixed valid/tampered rows (bit-flipped `c`, `z`, message, and pk), mixed
identity kinds in one block (nym/htlc rows stay host), empty batches,
min-batch routing, injected `batch.sign` faults degrading to host with
counters asserted, the shared identity parse cache, and (FTS_WARMUP=1
gated) a signature-batched block compiling zero new programs.

The device sign plane is forced ON via `BlockPolicy(sign_batched=True)`
here — the product default is `auto` (device only on real accelerators;
on this CPU-emulated plane a device Schnorr row costs ~3 orders of
magnitude more than the host check, so `auto` resolves to host).
"""

import os
import random

import pytest

from fabric_token_sdk_tpu.api.request import (
    IssueRecord,
    TokenRequest,
    TransferRecord,
)
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.crypto.serialization import dumps, loads
from fabric_token_sdk_tpu.drivers import identity
from fabric_token_sdk_tpu.drivers.fabtoken import (
    FabTokenDriver,
    FabTokenPublicParams,
)
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network, TxStatus
from fabric_token_sdk_tpu.services.network.orderer import (
    BlockValidationPipeline,
)
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx


def _counter(name):
    return mx.REGISTRY.counter(name).value


def _host_ok(pk: sign.PublicKey, msg: bytes, sig: bytes):
    try:
        pk.verify(msg, sig)
        return True
    except ValueError:
        return False


# ===================================================================
# Verifier-level differential (valid + every tamper class)
# ===================================================================


def test_batched_vs_host_verdicts_differential(rng):
    """Every row class — valid, bit-flipped c, bit-flipped z, flipped
    message, WRONG pk, unparseable blob — must agree with the host
    verdict (None = host re-verifies, used only for unparseable)."""
    from fabric_token_sdk_tpu.crypto.batch_sign import BatchedSchnorrVerifier

    keys = [sign.keygen(rng) for _ in range(3)]
    rows, expect = [], []

    def add(pk, msg, sig_raw, want=None):
        rows.append((pk.point, msg, sig_raw))
        expect.append(want if want is not None else _host_ok(pk, msg, sig_raw))

    for i in range(4):  # valid rows, repeated signers
        k = keys[i % 3]
        msg = b"pay-%d" % i
        add(k.public, msg, k.sign(msg, rng))
    # bit-flipped challenge
    d = loads(keys[0].sign(b"m-c", rng))
    d["c"] ^= 1
    add(keys[0].public, b"m-c", dumps(d))
    # bit-flipped response
    d = loads(keys[1].sign(b"m-z", rng))
    d["z"] ^= 1 << 7
    add(keys[1].public, b"m-z", dumps(d))
    # flipped message
    add(keys[2].public, b"other-message", keys[2].sign(b"m-msg", rng))
    # wrong pk for a valid signature
    add(keys[1].public, b"m-pk", keys[0].sign(b"m-pk", rng))
    # unparseable blob -> None (host reports the precise error)
    rows.append((keys[0].public.point, b"m-junk", b"\x00junk"))
    expect.append(None)
    # non-integer fields -> None (host decides; g1_mul(pt, True) would
    # silently coerce, so the device plane must not guess)
    d = loads(keys[2].sign(b"m-bool", rng))
    d["c"] = True
    rows.append((keys[2].public.point, b"m-bool", dumps(d)))
    expect.append(None)

    v = BatchedSchnorrVerifier()
    got = v.verify(rows)
    assert got == expect
    # the four valid rows really verified True
    assert got[:4] == [True] * 4
    # every tampered row is False on BOTH paths
    assert got[4:8] == [False] * 4


def test_empty_batch_is_clean():
    from fabric_token_sdk_tpu.crypto.batch_sign import BatchedSchnorrVerifier

    assert BatchedSchnorrVerifier().verify([]) == []


def test_host_verify_response_equation_unchanged(rng):
    """The folded-negation host path (sign.response_commitment) accepts
    every fresh signature and rejects tampered ones — the small-fix
    differential anchor."""
    k = sign.keygen(rng)
    sig = k.sign(b"hello", rng)
    k.public.verify(b"hello", sig)  # no raise
    d = loads(sig)
    com = sign.response_commitment(k.public.point, d["c"], d["z"])
    assert sign.challenge(k.public.point, com, b"hello") == d["c"]
    with pytest.raises(ValueError):
        k.public.verify(b"tampered", sig)


# ===================================================================
# Identity parse cache
# ===================================================================


def test_identity_cache_hits_and_bound(rng):
    identity.cache_clear()
    key = sign.keygen(rng)
    ident = identity.pk_identity(key.public)
    h0, m0 = _counter("identity.cache.hits"), _counter("identity.cache.misses")
    for i in range(3):
        identity.verify_signature(ident, b"m%d" % i, key.sign(b"m%d" % i, rng))
    assert _counter("identity.cache.misses") - m0 == 1
    assert _counter("identity.cache.hits") - h0 == 2
    # the batched collector shares the same entry
    pk = identity.public_key(ident)
    assert pk is not None and pk.point == key.public.point
    assert _counter("identity.cache.hits") - h0 == 3
    # non-pk and malformed identities yield no public key (and malformed
    # lookups are never cached)
    assert identity.public_key(identity.nym_identity((1, 2))) is None
    assert identity.public_key(b"not an identity") is None
    # bounded: a flood of distinct identities cannot grow it past capacity
    orig = identity._CACHE
    identity._CACHE = identity._IdentityCache(capacity=8)
    try:
        for i in range(40):
            kj = sign.keygen(rng)
            identity.public_key(identity.pk_identity(kj.public))
        assert identity.cache_len() == 8
    finally:
        identity._CACHE = orig
    identity.cache_clear()
    assert identity.cache_len() == 0


# ===================================================================
# Block-level wiring (fabtoken corpus: pk owners + issuer)
# ===================================================================


def _pk_corpus(tamper_kind=None, n_transfers=8):
    """1 issue seed + a chain of n pk-signed transfers; optionally
    tamper tx #2's owner signature (`bitflip` keeps the blob parseable —
    a device False verdict; `garbage` makes it unparseable — a None
    verdict the host loop rejects)."""
    pp = FabTokenPublicParams()
    drv = FabTokenDriver(pp)
    key = sign.keygen(random.Random(7))
    ident = identity.pk_identity(key.public)
    reqs = []
    out = drv.issue(ident, "USD", [9], [ident])
    req = TokenRequest(anchor="seed")
    req.issues.append(
        IssueRecord(action=out.action_bytes, issuer=ident,
                    outputs_metadata=out.metadata, receivers=[ident])
    )
    req.issues[0].signature = key.sign(req.marshal_to_sign(), random.Random(11))
    reqs.append(req.to_bytes())
    prev, prev_raw = ID("seed", 0), out.outputs[0]
    for k in range(n_transfers):
        t = drv.transfer([prev], [prev_raw], [prev_raw], "USD", [9], [ident])
        tr = TokenRequest(anchor=f"t{k}")
        tr.transfers.append(
            TransferRecord(action=t.action_bytes, input_ids=[prev],
                           senders=[ident], outputs_metadata=t.metadata,
                           receivers=[ident])
        )
        sig = key.sign(tr.marshal_to_sign(), random.Random(100 + k))
        if k == 2 and tamper_kind == "bitflip":
            d = loads(sig)
            d["z"] ^= 1
            sig = dumps(d)
        elif k == 2 and tamper_kind == "garbage":
            sig = b"\x00garbage"
        tr.transfers[0].signatures = [sig]
        reqs.append(tr.to_bytes())
        prev, prev_raw = ID(f"t{k}", 0), t.outputs[0]
    return pp, reqs


def _net(pp, **policy_over):
    policy = BlockPolicy(max_block_txs=16, **policy_over)
    return Network(RequestValidator(FabTokenDriver(pp)), policy=policy)


def _statuses(events):
    return [(e.tx_id, e.status) for e in events]


def test_block_verifies_all_signatures_in_one_pass():
    """Acceptance: a block of >= 8 pk-signed txs (8 owner sigs + 1
    issuer sig) verifies every parseable signature in ONE
    BatchedSchnorrVerifier pass, verdict-identical to the host path."""
    pp, reqs = _pk_corpus()
    b0, r0 = _counter("batch.sign.batches"), _counter("batch.sign.rows")
    dev = _net(pp, sign_batched=True, sign_min_batch=2)
    ev_dev = dev.submit_many(reqs)
    b1, r1 = _counter("batch.sign.batches"), _counter("batch.sign.rows")
    assert b1 - b0 == 1  # ONE batched call for the whole block
    assert r1 - r0 == 9  # issuer + 8 owners, all on device
    host = _net(pp, sign_batched=False)
    ev_host = host.submit_many(reqs)
    assert _counter("batch.sign.batches") == b1  # host path: no device call
    assert _statuses(ev_dev) == _statuses(ev_host)
    assert all(e.status == TxStatus.VALID for e in ev_dev)
    # the SEQUENTIAL engine (no verify/commit overlap) computes sign
    # verdicts inline and must agree too
    seq = _net(pp, sign_batched=True, sign_min_batch=2, pipeline=False)
    ev_seq = seq.submit_many(reqs)
    assert _counter("batch.sign.batches") - b1 == 1
    assert _statuses(ev_seq) == _statuses(ev_host)


@pytest.mark.parametrize("tamper_kind", ["bitflip", "garbage"])
def test_tampered_row_differential(tamper_kind):
    """A tampered owner signature — parseable (device False verdict) or
    unparseable (None -> host rejects) — invalidates exactly the txs the
    host path invalidates (the tampered tx and its broken chain)."""
    pp, reqs = _pk_corpus(tamper_kind=tamper_kind, n_transfers=5)
    ev_dev = _net(pp, sign_batched=True, sign_min_batch=2).submit_many(reqs)
    ev_host = _net(pp, sign_batched=False).submit_many(reqs)
    assert _statuses(ev_dev) == _statuses(ev_host)
    by_id = dict(_statuses(ev_dev))
    assert by_id["t2"] == TxStatus.INVALID
    assert by_id["t1"] == TxStatus.VALID
    dev_msg = {e.tx_id: e.message for e in ev_dev}
    assert "invalid owner signature" in dev_msg["t2"]


def test_min_batch_routes_small_blocks_host():
    pp, reqs = _pk_corpus(n_transfers=2)  # 3 obligations < min 4
    b0, h0 = _counter("batch.sign.batches"), _counter("batch.sign.host")
    ev = _net(pp, sign_batched=True, sign_min_batch=4).submit_many(reqs)
    assert all(e.status == TxStatus.VALID for e in ev)
    assert _counter("batch.sign.batches") == b0  # no device call
    assert _counter("batch.sign.host") - h0 == 3  # all routed host


def test_injected_fault_degrades_to_host():
    """An armed `batch.sign` fault drops every row of the block back to
    the host loop — verdicts unchanged, counters prove the degrade."""
    pp, reqs = _pk_corpus(tamper_kind="bitflip", n_transfers=5)
    f0 = _counter("batch.sign.host_fallbacks")
    b0 = _counter("batch.sign.batches")
    faults.arm("batch.sign", "error", count=1)
    try:
        ev = _net(pp, sign_batched=True, sign_min_batch=2).submit_many(reqs)
    finally:
        faults.disarm("batch.sign")
    assert _counter("batch.sign.host_fallbacks") - f0 == 6
    assert _counter("batch.sign.batches") == b0  # verify never completed
    assert _counter("faults.injected.batch.sign") >= 1
    by_id = dict(_statuses(ev))
    assert by_id["t2"] == TxStatus.INVALID  # host still rejects the tamper
    assert by_id["t1"] == TxStatus.VALID


def test_open_breaker_skips_collection():
    """An OPEN sign breaker (as left by construction failures) keeps
    the old latch's fast path: later blocks skip even the obligation
    collection (no per-block marshal/parse work, no re-import, no log
    spam) and host-verify everything — but unlike the latch, the plane
    re-engages via the half-open probe once the cooldown expires
    (pinned in tests/test_resilience.py)."""
    from fabric_token_sdk_tpu.utils import resilience

    pp, reqs = _pk_corpus(n_transfers=4)
    pipeline = BlockValidationPipeline(
        RequestValidator(FabTokenDriver(pp)),
        BlockPolicy(sign_batched=True, sign_min_batch=2),
    )
    brk = resilience.breaker("sign")
    brk.cooldown_s = 60.0  # hold the breaker open for the whole test
    brk.record_failure(timeout=True)
    brk.record_failure(timeout=True)  # consecutive timeouts: OPEN
    assert brk.state == "open"
    before = {
        n: _counter(n) for n in
        ("batch.sign.host_fallbacks", "batch.sign.batches",
         "batch.sign.host", "batch.sign.rows")
    }
    requests = [TokenRequest.from_bytes(rb) for rb in reqs]
    assert pipeline.sign_verdicts(requests) == {}
    for name, v in before.items():
        assert _counter(name) == v, name  # no work, no counters


def test_auto_mode_resolves_host_on_cpu():
    """The product default (`sign_batched=None` = auto) must resolve to
    the host path on this CPU backend — the emulated device plane is
    slower than host Schnorr, same asymmetry as the prove plane."""
    pp, reqs = _pk_corpus(n_transfers=4)
    pipeline = BlockValidationPipeline(
        RequestValidator(FabTokenDriver(pp)), BlockPolicy()
    )
    assert pipeline.sign_enabled() is False
    b0 = _counter("batch.sign.batches")
    ev = _net(pp).submit_many(reqs)  # default policy: auto
    assert all(e.status == TxStatus.VALID for e in ev)
    assert _counter("batch.sign.batches") == b0


# ===================================================================
# Mixed identity kinds: nym/htlc obligations stay host
# ===================================================================


def test_mixed_identity_kinds_collection(rng):
    """Collection-level contract: in one block of fabtoken txs whose
    claimed owners span pk / nym / htlc kinds, only the pk obligations
    become device rows — nym and htlc rows are counted host and get NO
    verdict (the host loop would verify them unchanged)."""
    pp = FabTokenPublicParams()
    drv = FabTokenDriver(pp)
    key = sign.keygen(rng)
    pk_ident = identity.pk_identity(key.public)
    nym_ident = identity.nym_identity((3, 4))
    htlc_ident = identity.htlc_identity({"probe": 1})

    def transfer_req(anchor, owner_ident):
        from fabric_token_sdk_tpu.models.token import Owner, Token

        raw = Token(Owner(owner_ident), "USD", hex(5)).to_bytes()
        tid = ID("seed-" + anchor, 0)
        t = drv.transfer([tid], [raw], [raw], "USD", [5], [pk_ident])
        req = TokenRequest(anchor=anchor)
        req.transfers.append(
            TransferRecord(action=t.action_bytes, input_ids=[tid],
                           senders=[owner_ident], outputs_metadata=t.metadata,
                           receivers=[pk_ident])
        )
        req.transfers[0].signatures = [key.sign(req.marshal_to_sign(), rng)]
        return req

    requests = [
        transfer_req("pk-a", pk_ident),
        transfer_req("pk-b", pk_ident),
        transfer_req("nym-a", nym_ident),
        transfer_req("htlc-a", htlc_ident),
    ]
    pipeline = BlockValidationPipeline(
        RequestValidator(FabTokenDriver(pp)),
        BlockPolicy(sign_batched=True, sign_min_batch=2),
    )
    h0, r0 = _counter("batch.sign.host"), _counter("batch.sign.rows")
    verdicts = pipeline.sign_verdicts(requests)
    assert _counter("batch.sign.rows") - r0 == 2  # the two pk rows
    assert _counter("batch.sign.host") - h0 == 2  # nym + htlc stay host
    assert set(verdicts) == {0, 1}
    for ti in (0, 1):
        ((okey, (ident_bytes, ok)),) = verdicts[ti].items()
        assert okey == ("transfer", 0, 0)
        assert ident_bytes == pk_ident
        assert ok is True


def test_auditor_and_issue_obligations_batched(rng):
    """Auditor + issuer signatures join the same batched pass, keyed by
    their own obligation kinds, and a tampered auditor signature is a
    device False that rejects the request — identically to host."""
    pp = FabTokenPublicParams()
    auditor_key = sign.keygen(rng)
    auditor_ident = identity.pk_identity(auditor_key.public)
    pp2, reqs = _pk_corpus(n_transfers=4)
    pp2.add_auditor(auditor_ident)

    def audited(reqs_bytes, tamper_idx=None):
        out = []
        for i, rb in enumerate(reqs_bytes):
            req = TokenRequest.from_bytes(rb)
            req.auditor_signature = auditor_key.sign(
                req.marshal_to_audit(), rng
            )
            if i == tamper_idx:
                d = loads(req.auditor_signature)
                d["c"] ^= 1
                req.auditor_signature = dumps(d)
            out.append(req.to_bytes())
        return out

    def audited_net(sign_batched):
        return Network(
            RequestValidator(FabTokenDriver(pp2), auditor_ident),
            policy=BlockPolicy(max_block_txs=16, sign_batched=sign_batched,
                               sign_min_batch=2),
        )

    corpus = audited(reqs, tamper_idx=3)
    r0 = _counter("batch.sign.rows")
    ev_dev = audited_net(True).submit_many(corpus)
    # 5 auditor sigs + 1 issuer sig + 4 owner sigs in the one pass
    assert _counter("batch.sign.rows") - r0 == 10
    ev_host = audited_net(False).submit_many(corpus)
    assert _statuses(ev_dev) == _statuses(ev_host)
    by_id = dict(_statuses(ev_dev))
    assert by_id["t2"] == TxStatus.INVALID  # the tampered auditor sig
    msg = {e.tx_id: e.message for e in ev_dev}["t2"]
    assert "invalid auditor signature" in msg


# ===================================================================
# Soak plumbing: driver option + sign/host_validate reporting
# ===================================================================


class _PhaseStub:
    def set_phase(self, name, **attrs):
        pass


def _run_soak(monkeypatch, tmp_path, **env):
    import bench

    defaults = {
        "FTS_BENCH_SOAK_S": "1.2",
        "FTS_BENCH_SOAK_CLIENTS": "1",
        "FTS_BENCH_SOAK_GROUP": "2",
        "FTS_SIGN_BATCHED": "0",  # emulated device plane: host loop
        "FTS_BENCH_HISTORY": str(tmp_path / "hist.jsonl"),
    }
    defaults.update(env)
    for k, v in defaults.items():
        monkeypatch.setenv(k, v)
    return bench._soak(_PhaseStub())


def test_soak_reports_sign_plane_and_host_validate_frac(
    monkeypatch, tmp_path
):
    """The fabtoken soak section carries the new schema-validated
    fields: driver, sign_plane, host_validate_frac, sign/identity-cache
    deltas — and validates under the shared schema."""
    from fabric_token_sdk_tpu.utils import benchschema

    soak = _run_soak(monkeypatch, tmp_path)
    assert benchschema.validate_soak(soak) == []
    assert soak["driver"] == "fabtoken"
    assert soak["sign_plane"] == "host"  # FTS_SIGN_BATCHED=0
    assert soak["txs"] > 0
    assert soak["host_validate_frac"] is not None
    assert 0.0 <= soak["host_validate_frac"] <= 1.0
    assert soak["sign_rows"] == 0 and soak["sign_fallbacks"] == 0
    # pk identities repeat every tx: the cache must be nearly all hits
    assert soak["identity_cache_hit_rate"] is not None
    assert soak["identity_cache_hit_rate"] > 0.5


@pytest.mark.slow
def test_soak_zkatdlog_driver(monkeypatch, tmp_path, rng):
    """FTS_BENCH_SOAK_DRIVER=zkatdlog drives chained 1-in/1-out zk
    transfers through the same soak engine (host-proved; proof plane
    disabled here — the emulated device path would eat the budget)."""
    from fabric_token_sdk_tpu.crypto.setup import setup
    from fabric_token_sdk_tpu.utils import benchschema

    import bench

    zk_pp = setup(base=4, exponent=2, rng=rng)
    for k, v in {
        "FTS_BENCH_SOAK_S": "1.2",
        "FTS_BENCH_SOAK_CLIENTS": "1",
        "FTS_BENCH_SOAK_GROUP": "2",
        "FTS_BENCH_SOAK_DRIVER": "zkatdlog",
        "FTS_SIGN_BATCHED": "0",
        "FTS_BLOCK_BATCHED": "0",
        "FTS_BENCH_HISTORY": str(tmp_path / "hist.jsonl"),
    }.items():
        monkeypatch.setenv(k, v)
    soak = bench._soak(_PhaseStub(), zk_pp=zk_pp)
    assert benchschema.validate_soak(soak) == []
    assert soak["driver"] == "zkatdlog"
    assert soak["txs"] > 0


def test_soak_schema_optional_fields():
    """The new soak fields are OPTIONAL (older history rounds predate
    them and must stay gate-eligible) but type-checked when present."""
    from fabric_token_sdk_tpu.utils import benchschema

    base = {"steady_txs_per_s": 100.0, "p99_finality_s": 0.5,
            "queue_depth_max": 10, "backpressure_rejects": 0}
    assert benchschema.validate_soak(base) == []  # PR-12-era round
    full = dict(base, driver="fabtoken", sign_plane="host",
                host_validate_frac=0.4, sign_rows=0, sign_host=12,
                sign_fallbacks=0, identity_cache_hit_rate=0.97)
    assert benchschema.validate_soak(full) == []
    assert benchschema.validate_soak(dict(base, driver=7))
    assert benchschema.validate_soak(dict(base, host_validate_frac="x"))
    assert benchschema.validate_soak(dict(base, sign_rows=0.5))


# ===================================================================
# Compile budget (FTS_WARMUP-gated)
# ===================================================================


@pytest.mark.skipif(
    os.environ.get("FTS_WARMUP") != "1",
    reason="needs the FTS_WARMUP=1 session precompile (conftest fixture)",
)
def test_sign_batched_block_compiles_zero_programs():
    """Post-warmup, a signature-batched block compiles NOTHING and
    misses the persistent cache zero times: the sign plane is a
    composition of already-canonical tiles (msm1/mul/sub)."""
    COMPILES = "jax.core.compile.backend_compile_duration.seconds"
    pp, reqs = _pk_corpus()
    # absorb the one-time persistent-cache loads of the tile programs
    warm_pp, warm_reqs = _pk_corpus(n_transfers=3)
    _net(warm_pp, sign_batched=True, sign_min_batch=2).submit_many(warm_reqs)
    c0 = mx.REGISTRY.histogram(COMPILES).count
    m0 = _counter("jax.compilation_cache.cache_misses")
    r0 = _counter("batch.sign.rows")
    ev = _net(pp, sign_batched=True, sign_min_batch=2).submit_many(reqs)
    assert all(e.status == TxStatus.VALID for e in ev)
    assert _counter("batch.sign.rows") - r0 == 9  # the plane really ran
    assert mx.REGISTRY.histogram(COMPILES).count - c0 == 0
    assert _counter("jax.compilation_cache.cache_misses") - m0 == 0
