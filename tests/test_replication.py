"""Replicated ledger plane: WAL shipping, fencing epochs, failover.

What is pinned here (services/network/replication.py + the follower
apply path in ledger.py + the client failover path in remote.py):

* `WriteAheadLog.replay_iter(from_offset)` — offset-resumable streaming
  replay with torn-tail truncation (the follower-tailing primitive).
* Leader→follower shipping: journal catch-up, snapshot bootstrap,
  streaming deltas through the no-reverify replay path, lag via
  `ops.health`.
* Fencing epochs: stale frames answered with typed `StaleEpoch` (the
  zombie's appends are REFUSED, never merged); a zombie leader demotes
  itself on contact with a higher epoch.
* Promotion: explicit `promote` RPC and the lease watchdog
  (auto-promote), both epoch-bump-first and crash-persistent.
* Degrade-only: `FTS_REPL=0` / zero followers leave the commit path
  byte-identical; a hung or dead follower never stalls a commit.
* Client failover: endpoint lists, leader rediscovery by highest
  epoch, exactly-once across the switch.
* The kill-the-leader chaos soak (slow): SIGKILL a leader subprocess
  mid-workload, promote the follower, assert zero acked-tx loss, zero
  duplicate commits, bounded failover, and live fencing.
"""

import os
import random
import select
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from fabric_token_sdk_tpu.api.request import IssueRecord, TokenRequest
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.drivers import identity
from fabric_token_sdk_tpu.drivers.fabtoken import (
    FabTokenDriver,
    FabTokenPublicParams,
)
from fabric_token_sdk_tpu.services.network import TxStatus, replication
from fabric_token_sdk_tpu.services.network.ledger import Network
from fabric_token_sdk_tpu.services.network.remote import (
    LedgerServer,
    RemoteError,
    RemoteNetwork,
    _parse_endpoints,
    _recv_msg,
    _send_msg,
)
from fabric_token_sdk_tpu.services.network.wal import WriteAheadLog
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    return mx.REGISTRY.counter(name).value


def _wait(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _issue_bytes(drv, key, ident, rng, anchor, value=5):
    outcome = drv.issue(ident, "USD", [value], [ident], anonymous=False)
    req = TokenRequest(anchor=anchor)
    req.issues.append(
        IssueRecord(action=outcome.action_bytes, issuer=ident,
                    outputs_metadata=outcome.metadata, receivers=[ident])
    )
    req.issues[0].signature = key.sign(req.marshal_to_sign(), rng)
    return req.to_bytes()


def _client_kit(seed=0xF75):
    rng = random.Random(seed)
    pp = FabTokenPublicParams()
    drv = FabTokenDriver(pp)
    key = sign.keygen(rng)
    ident = identity.pk_identity(key.public)
    return pp, drv, key, ident, rng


def _fab_net(wal_path, pp=None, snapshot_every=0):
    pp = pp or FabTokenPublicParams()
    return Network(
        RequestValidator(FabTokenDriver(pp)), wal_path=str(wal_path),
        snapshot_every=snapshot_every,
    )


def _raw_rpc(address, msg, timeout=5.0):
    """One framed request/response over a fresh socket — the zombie's
    wire view, below every client nicety."""
    with socket.create_connection(address, timeout=timeout) as sock:
        sock.settimeout(timeout)
        _send_msg(sock, msg)
        return _recv_msg(sock)


# ===================================================================
# replay_iter: the follower-tailing / recovery-streaming primitive
# ===================================================================


def test_replay_iter_stream_offsets_and_resume(tmp_path):
    wal = WriteAheadLog(tmp_path / "t.wal")
    payloads = [b"alpha", b"", b"\x00" * 512, b"tail"]
    for p in payloads:
        wal.append(p)
    got = list(wal.replay_iter())
    assert [p for _off, p in got] == payloads
    # offsets strictly increase and the last one is the journal size
    offsets = [off for off, _p in got]
    assert offsets == sorted(set(offsets))
    assert offsets[-1] == os.path.getsize(wal.path)
    # resuming from any yielded offset streams exactly the suffix
    for i, (off, _p) in enumerate(got):
        assert [p for _o, p in wal.replay_iter(off)] == payloads[i + 1:]
    # replay() is the materialized equivalent
    assert wal.replay() == payloads
    wal.close()


def test_replay_iter_stale_scan_never_truncates_live_journal(tmp_path):
    """A replay scan that started BEFORE a compaction must never act on
    the regrown journal: its offsets point into a file that no longer
    exists, so a CRC mismatch mid-record there is a stale verdict, not a
    torn tail — truncating would cut live fsync'd records in half."""
    wal = WriteAheadLog(tmp_path / "t.wal")
    wal.append(b"one")
    wal.append(b"two-a-longer-record")
    stale = wal.replay_iter()
    next(stale)  # scan begins: size + generation captured pre-compaction
    # leader compacts and regrows: boundaries shift under the stale scan
    wal.reset()
    wal.append(b"a")
    wal.append(b"b" * 64)
    torn_before = _counter("wal.torn_tails")
    list(stale)  # drains over garbage at old offsets; must be a no-op
    assert _counter("wal.torn_tails") == torn_before
    assert wal.replay() == [b"a", b"b" * 64]
    wal.close()


def test_replay_iter_truncates_torn_tail(tmp_path):
    path = tmp_path / "t.wal"
    wal = WriteAheadLog(path)
    wal.append(b"one")
    wal.append(b"two")
    good_size = os.path.getsize(path)
    before = _counter("wal.torn_tails")
    with open(path, "ab") as fh:
        fh.write(struct.pack(">II", 4096, 0xDEAD) + b"fragment")
    assert [p for _o, p in wal.replay_iter()] == [b"one", b"two"]
    assert _counter("wal.torn_tails") - before == 1
    # the torn bytes are GONE from disk, not just skipped
    assert os.path.getsize(path) == good_size
    wal.append(b"three")
    assert wal.replay() == [b"one", b"two", b"three"]
    wal.close()


# ===================================================================
# degrade-only: disabled / followerless replication is a no-op
# ===================================================================


def test_attach_is_degrade_only(tmp_path, monkeypatch):
    pp, drv, key, ident, rng = _client_kit()
    # FTS_REPL=0: both attach functions answer None, repl stays unset
    monkeypatch.setenv("FTS_REPL", "0")
    net = _fab_net(tmp_path / "off.wal", pp)
    assert replication.attach_leader(net, [("127.0.0.1", 1)]) is None
    assert replication.attach_follower(net) is None
    assert net.repl is None
    monkeypatch.delenv("FTS_REPL")
    # zero followers: same no-op by construction
    assert replication.attach_leader(net, []) is None
    assert net.repl is None
    # the commit path is byte-identical to a standalone node: the WAL
    # record of the same tx matches a never-attached twin exactly
    req = _issue_bytes(drv, key, ident, rng, "solo-1")
    ev = net.submit(req)
    assert ev.status == TxStatus.VALID
    twin = _fab_net(tmp_path / "twin.wal", pp)
    ev = twin.submit(req)
    assert ev.status == TxStatus.VALID
    rec_a = WriteAheadLog(tmp_path / "off.wal").replay()
    rec_b = WriteAheadLog(tmp_path / "twin.wal").replay()
    assert len(rec_a) == len(rec_b) == 1

    def _stable(raw):
        import json
        d = json.loads(raw)
        d.pop("ts", None)
        return d

    assert _stable(rec_a[0]) == _stable(rec_b[0])
    # a leader NEEDS a journal: shipping rides the WAL
    plain = Network(RequestValidator(FabTokenDriver(pp)))
    with pytest.raises(replication.ReplicationError):
        replication.attach_leader(plain, [("127.0.0.1", 1)])
    # a follower NEEDS a durable epoch home too: without one a restart
    # comes back at epoch 0 and fencing does not survive the crash
    with pytest.raises(replication.ReplicationError):
        replication.attach_follower(plain)
    # ... unless an explicit epoch_path supplies the durability
    state = replication.attach_follower(
        plain, epoch_path=str(tmp_path / "plain.epoch")
    )
    assert state is not None
    state.close()


# ===================================================================
# shipping: catch-up, streaming, lag, promotion
# ===================================================================


def test_ship_catchup_health_and_promotion(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    # journal history BEFORE the follower exists: catch-up must stream it
    for i in range(2):
        ev = leader_net.submit(_issue_bytes(drv, key, ident, rng, f"pre-{i}"))
        assert ev.status == TxStatus.VALID
    follower_srv = LedgerServer(network=follower_net).start()
    leader_srv = LedgerServer(network=leader_net).start()
    applied_before = _counter("repl.applied.records")
    try:
        replication.attach_follower(follower_net)
        state = replication.attach_leader(
            leader_net, [follower_srv.address], heartbeat_s=0.1
        )
        assert state is not None and leader_net.repl is state
        _wait(lambda: follower_net.height() == leader_net.height(),
              what="follower catch-up")
        # live commit flows as a delta through the no-reverify path
        ev = leader_net.submit(_issue_bytes(drv, key, ident, rng, "live-0"))
        assert ev.status == TxStatus.VALID
        _wait(lambda: follower_net.height() == leader_net.height(),
              what="live delta")
        assert _counter("repl.applied.records") - applied_before == 3
        # the follower holds the leader's verdicts without re-endorsing
        assert follower_net.status("pre-0").status == TxStatus.VALID
        assert follower_net.status("live-0").status == TxStatus.VALID
        # lag and role ride ops.health on both sides
        lh = leader_srv.network.health()["repl"]
        assert lh["role"] == "leader"
        assert lh["followers"][0]["state"] == "streaming"
        assert lh["lag"] == 0
        fh = follower_net.health()["repl"]
        assert fh["role"] == "follower" and fh["lag"] == 0
        # a standalone node publishes NO repl section (ftstop old-node
        # contract), and ftstop renders the column from the section
        standalone = _fab_net(tmp_path / "alone.wal", pp)
        assert standalone.health()["repl"] is None
        sys.path.insert(0, os.path.join(REPO_ROOT, "cmd"))
        try:
            import ftstop
        finally:
            sys.path.pop(0)
        row = ftstop.format_row(leader_srv.network.health(),
                                {"counters": {}, "gauges": {},
                                 "histograms": {}}, None, None)
        assert "repl=leader@e0 lag=0" in row
        # explicit promotion over the wire: epoch bumps and persists
        client = RemoteNetwork(follower_srv.address, timeout=5,
                               retries=2, backoff_s=0.01)
        promotions_before = _counter("repl.promotions")
        assert client.promote() == 1
        assert client.promote() == 1  # idempotent on a leader
        assert _counter("repl.promotions") - promotions_before == 1
        assert replication._load_epoch(
            str(tmp_path / "follower.wal.epoch")) == 1
        # the promoted node now accepts submits directly
        ev = client.submit(_issue_bytes(drv, key, ident, rng, "post-promo"))
        assert ev.status == TxStatus.VALID
        client.close()
    finally:
        leader_srv.stop()
        follower_srv.stop()


def test_acked_commit_is_on_follower_before_submit_returns(tmp_path):
    """The ack watermark is the follower's POST-apply height: a commit's
    bounded ship wait must cover the record just committed, not merely
    confirm the PREVIOUS record's replication — otherwise the newest
    acked tx is always the unreplicated one when the leader dies."""
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    try:
        replication.attach_follower(follower_net)
        replication.attach_leader(leader_net, [follower_srv.address])
        _wait(lambda: leader_net.repl.shipper.link_states()[0]["state"]
              == "streaming", what="link streaming")
        for i in range(3):
            ev = leader_net.submit(
                _issue_bytes(drv, key, ident, rng, f"sync-{i}")
            )
            assert ev.status == TxStatus.VALID
            # NO wait: by the time the submitter holds the ack, the
            # streaming follower must already hold the block
            assert follower_net.height() == leader_net.height()
            assert follower_net.status(f"sync-{i}").status == TxStatus.VALID
    finally:
        follower_srv.stop()
        leader_net.repl.close()


def test_snapshot_bootstrap_for_compacted_leader(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    # snapshot_every=1: every commit compacts, so the journal never
    # covers history — a fresh follower MUST bootstrap via snapshot
    leader_net = _fab_net(tmp_path / "leader.wal", pp, snapshot_every=1)
    for i in range(3):
        ev = leader_net.submit(_issue_bytes(drv, key, ident, rng, f"c-{i}"))
        assert ev.status == TxStatus.VALID
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    boots_before = _counter("repl.bootstraps")
    sent_before = _counter("repl.bootstraps.sent")
    try:
        replication.attach_follower(follower_net)
        replication.attach_leader(leader_net, [follower_srv.address])
        _wait(lambda: follower_net.height() == leader_net.height(),
              what="snapshot bootstrap")
        assert _counter("repl.bootstraps") - boots_before == 1
        assert _counter("repl.bootstraps.sent") - sent_before == 1
        assert follower_net.status("c-2").status == TxStatus.VALID
    finally:
        follower_srv.stop()
        leader_net.repl.close()


# ===================================================================
# fencing: stale appends refused, zombies demoted — never merged
# ===================================================================


def test_fencing_rejects_stale_frames_and_demotes_zombies(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    node_net = _fab_net(tmp_path / "node.wal", pp)
    node_srv = LedgerServer(network=node_net).start()
    try:
        state = replication.attach_follower(node_net)
        state.promote(reason="test")  # epoch 0 -> 1
        stale_before = _counter("repl.stale_rejected")
        # the zombie's raw append at its old epoch: typed refusal
        resp = _raw_rpc(node_srv.address, {
            "op": "repl.ship", "epoch": 0, "record": b"junk".hex(),
        })
        assert resp["ok"] is False
        assert resp["error_class"] == "StaleEpoch"
        assert resp["epoch"] == 1  # the fencer's ACTUAL epoch rides along
        assert _counter("repl.stale_rejected") - stale_before == 1
        # a LEADER also refuses its own epoch: promotion always bumps,
        # so an equal-epoch frame can only be a second leader (split
        # brain), never a colleague
        resp = _raw_rpc(node_srv.address, {
            "op": "repl.ship", "epoch": 1, "record": b"junk".hex(),
        })
        assert resp["ok"] is False
        assert resp["error_class"] == "StaleEpoch"
        height_before = node_net.height()
        # a full zombie LEADER (epoch 0, divergent journal) reattaching:
        # the repl.state handshake teaches it the higher epoch and it
        # demotes itself — nothing of its journal is ever merged
        zombie_net = _fab_net(tmp_path / "zombie.wal", pp)
        ev = zombie_net.submit(_issue_bytes(drv, key, ident, rng, "z-0"))
        assert ev.status == TxStatus.VALID
        demotions_before = _counter("repl.demotions")
        zombie_state = replication.attach_leader(
            zombie_net, [node_srv.address]
        )
        _wait(lambda: zombie_state.role == "follower",
              what="zombie self-demotion")
        assert _counter("repl.demotions") - demotions_before == 1
        assert zombie_state.epoch >= 1  # adopted the fencing epoch
        time.sleep(0.2)  # any in-flight zombie frames land (and bounce)
        assert node_net.height() == height_before
        assert node_net.status("z-0") is None
        zombie_state.close()
    finally:
        node_srv.stop()


def test_fenced_leader_adopts_fencers_actual_epoch(tmp_path):
    """A fenced zombie demotes to the fencer's ACTUAL epoch (it rides
    the typed `StaleEpoch` answer), not a guessed `epoch + 1` — the
    guess would let a later re-promotion land EQUAL to the real leader's
    epoch, and equal-epoch leaders would merge each other's frames."""
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    try:
        replication.attach_follower(follower_net)
        # huge heartbeat: the only traffic after streaming is the ship
        # below, so the fence verdict deterministically rides IT
        state = replication.attach_leader(
            leader_net, [follower_srv.address], heartbeat_s=60.0
        )
        _wait(lambda: state.shipper.link_states()[0]["state"]
              == "streaming", what="link streaming")
        # walk the follower to a HIGH epoch (promote bumps, demote at an
        # equal epoch only flips the role back), then lead at epoch 5
        fstate = follower_net.repl
        for _ in range(4):
            fstate.promote(reason="cycle")
            fstate.demote(0, "cycle")
        fstate.promote(reason="final")
        assert fstate.epoch == 5
        ev = leader_net.submit(_issue_bytes(drv, key, ident, rng, "fence"))
        assert ev.status == TxStatus.VALID  # degrade-only: commit stands
        _wait(lambda: state.role == "follower", what="zombie demotion")
        assert state.epoch == 5, (
            f"demoted to guessed epoch {state.epoch}, not the fencer's"
        )
    finally:
        follower_srv.stop()
        state.close()


def test_auto_promote_lease_watchdog(tmp_path, monkeypatch):
    monkeypatch.setenv("FTS_REPL_LEASE_S", "0.3")
    net = _fab_net(tmp_path / "f.wal")
    promotions_before = _counter("repl.promotions")
    state = replication.attach_follower(net, auto_promote=True)
    try:
        _wait(lambda: state.role == "leader", timeout=5.0,
              what="lease-expiry auto-promotion")
        assert _counter("repl.promotions") - promotions_before == 1
        assert state.epoch == 1
        # the epoch survived the promotion durably: a restart from the
        # same paths comes back fenced-high
        reborn = replication.attach_follower(net)
        assert reborn.epoch == 1
        reborn.close()
    finally:
        state.close()


# ===================================================================
# degrade-only under misbehaving followers
# ===================================================================


def test_hung_follower_never_stalls_commit(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    try:
        replication.attach_follower(follower_net)
        replication.attach_leader(
            leader_net, [follower_srv.address], ship_timeout_s=0.3
        )
        _wait(lambda: leader_net.repl.shipper.link_states()[0]["state"]
              == "streaming", what="link streaming")
        timeouts_before = _counter("repl.ship.ack_timeouts")
        # hang the NEXT ship on the link thread; the bounded ack wait
        # must release the commit path long before the hang ends
        faults.arm("repl.ship", "hang", count=1, delay_s=5.0)
        t0 = time.monotonic()
        ev = leader_net.submit(_issue_bytes(drv, key, ident, rng, "hung-0"))
        wall = time.monotonic() - t0
        assert ev.status == TxStatus.VALID
        assert wall < 3.0, f"commit stalled {wall:.1f}s behind a hung link"
        assert _counter("repl.ship.ack_timeouts") - timeouts_before >= 1
        faults.clear()  # release the hung link thread
        # the link recovers and the follower still converges
        _wait(lambda: follower_net.height() == leader_net.height(),
              what="post-hang convergence")
    finally:
        faults.clear()
        follower_srv.stop()
        leader_net.repl.close()


def test_dead_follower_never_stalls_commit(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    # a port with no listener: the link can never reach streaming
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_addr = s.getsockname()
    state = replication.attach_leader(
        leader_net, [dead_addr], ship_timeout_s=0.3, queue_max=2
    )
    try:
        dropped_before = _counter("repl.ship.dropped")
        unsynced_before = _counter("repl.ship.unsynced")
        t0 = time.monotonic()
        for i in range(4):
            ev = leader_net.submit(
                _issue_bytes(drv, key, ident, rng, f"dead-{i}")
            )
            assert ev.status == TxStatus.VALID
        wall = time.monotonic() - t0
        assert wall < 5.0, f"commits stalled {wall:.1f}s behind a dead link"
        # the bounded queue overflowed LOUDLY instead of growing
        assert _counter("repl.ship.dropped") - dropped_before >= 2
        # ... and every skipped ack wait on the never-streaming link is
        # visible too, not silently uncounted
        assert _counter("repl.ship.unsynced") - unsynced_before >= 4
        assert state.shipper.link_states()[0]["state"] != "streaming"
    finally:
        state.close()


def test_node_stopped_follower_ends_link_cleanly(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    replication.attach_follower(follower_net)
    state = replication.attach_leader(
        leader_net, [follower_srv.address], heartbeat_s=0.05
    )
    try:
        _wait(lambda: state.shipper.link_states()[0]["state"] == "streaming",
              what="link streaming")
        stopped_before = _counter("repl.link.node_stopped")
        follower_srv.stop()
        _wait(lambda: state.shipper.link_states()[0]["state"] == "stopped",
              what="clean link stop")
        assert _counter("repl.link.node_stopped") - stopped_before == 1
        # an orderly stop is a demotion signal, not a retry storm: the
        # link thread has exited for good
        errors_before = _counter("repl.link.errors")
        time.sleep(0.3)
        assert _counter("repl.link.errors") == errors_before
    finally:
        state.close()


# ===================================================================
# typed answers + client failover
# ===================================================================


def test_follower_submit_rejected_typed_not_leader(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    follower_net = _fab_net(tmp_path / "f.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    try:
        replication.attach_follower(follower_net)
        nl_before = _counter("remote.dispatch.not_leader")
        req = _issue_bytes(drv, key, ident, rng, "nope")
        # the wire answer is TYPED, so clients can distinguish "ask the
        # leader" from a real failure
        resp = _raw_rpc(follower_srv.address,
                        {"op": "submit", "request": req.hex()})
        assert resp["ok"] is False
        assert resp["error_class"] == "NotLeader"
        assert _counter("remote.dispatch.not_leader") == nl_before + 1
        # a single-endpoint client (no failover candidates) surfaces the
        # TYPED refusal after exhausting retries instead of hanging or
        # degrading it to transport noise
        client = RemoteNetwork(follower_srv.address, timeout=5,
                               retries=1, backoff_s=0.01)
        with pytest.raises(RemoteError) as exc:
            client.submit(req)
        assert exc.value.error_class == "NotLeader"
        client.close()
        # and the follower recorded NO verdict for it
        assert follower_net.status("nope") is None
    finally:
        follower_srv.stop()


def test_repl_ops_on_standalone_answer_typed(tmp_path):
    net = _fab_net(tmp_path / "s.wal")
    srv = LedgerServer(network=net).start()
    try:
        resp = _raw_rpc(srv.address, {"op": "repl.ship", "epoch": 0,
                                      "record": b"x".hex()})
        assert resp["ok"] is False
        assert resp["error_class"] == "ReplicationDisabled"
        resp = _raw_rpc(srv.address, {"op": "promote"})
        assert resp["ok"] is False
        assert resp["error_class"] == "ReplicationDisabled"
    finally:
        srv.stop()


def test_parse_endpoints_and_env(tmp_path, monkeypatch):
    assert _parse_endpoints("a:1,b:2 , c:3") == [
        ("a", 1), ("b", 2), ("c", 3)
    ]
    with pytest.raises(ValueError):
        _parse_endpoints("no-port")
    net = _fab_net(tmp_path / "s.wal")
    srv = LedgerServer(network=net).start()
    try:
        host, port = srv.address
        monkeypatch.setenv(
            "FTS_REMOTE_ENDPOINTS", f"{host}:{port},{host}:{port + 1}"
        )
        client = RemoteNetwork(timeout=5, retries=1, backoff_s=0.01)
        assert client.endpoints == [(host, port), (host, port + 1)]
        assert client.address == (host, port)
        assert client.height() == 0
        client.close()
        with pytest.raises(ValueError):
            monkeypatch.setenv("FTS_REMOTE_ENDPOINTS", "")
            RemoteNetwork()
    finally:
        srv.stop()


def test_client_failover_rides_exactly_once(tmp_path):
    pp, drv, key, ident, rng = _client_kit()
    leader_net = _fab_net(tmp_path / "leader.wal", pp)
    follower_net = _fab_net(tmp_path / "follower.wal", pp)
    follower_srv = LedgerServer(network=follower_net).start()
    leader_srv = LedgerServer(network=leader_net).start()
    replication.attach_follower(follower_net)
    replication.attach_leader(leader_net, [follower_srv.address])
    client = RemoteNetwork(endpoints=[leader_srv.address,
                                      follower_srv.address],
                           timeout=5, retries=8, backoff_s=0.05)
    try:
        ev = client.submit(_issue_bytes(drv, key, ident, rng, "pre-kill"))
        assert ev.status == TxStatus.VALID
        _wait(lambda: follower_net.height() == leader_net.height(),
              what="replication of the acked tx")
        switches_before = _counter("remote.failover.switches")
        leader_srv.stop()
        follower_net.repl.promote(reason="test failover")
        # the SAME client object survives the switch: the next submit
        # rediscovers the promoted leader and commits exactly once
        ev = client.submit(_issue_bytes(drv, key, ident, rng, "post-kill"))
        assert ev.status == TxStatus.VALID
        assert _counter("remote.failover.switches") - switches_before >= 1
        assert client.address == follower_srv.address
        # nothing acked was lost and nothing doubled
        assert client.status("pre-kill").status == TxStatus.VALID
        assert client.status("post-kill").status == TxStatus.VALID
        assert follower_net.height() == 2
    finally:
        client.close()
        follower_srv.stop()


# ===================================================================
# the kill-the-leader chaos soak (slow)
# ===================================================================

_REPL_CHILD = """
import os, sys, threading
sys.path.insert(0, sys.argv[4])
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.services.network.ledger import Network
from fabric_token_sdk_tpu.services.network.remote import LedgerServer
from fabric_token_sdk_tpu.services.network import replication

wal_path, role, peer = sys.argv[1], sys.argv[2], sys.argv[3]
validator = RequestValidator(FabTokenDriver(FabTokenPublicParams()))
net = Network(validator, wal_path=wal_path)
server = LedgerServer(network=net).start()
if role == "follower":
    replication.attach_follower(net)
elif role == "leader":
    host, _, port = peer.rpartition(":")
    replication.attach_leader(net, [(host, int(port))])
print("READY", server.address[1], flush=True)
threading.Event().wait()
"""


def _spawn_repl_node(wal_path, role, peer="-"):
    proc = subprocess.Popen(
        [sys.executable, "-c", _REPL_CHILD, str(wal_path), role, peer,
         REPO_ROOT],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu", FTS_BLOCK_BATCHED="0"),
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"repl child died rc={proc.returncode}:\n{proc.stderr.read()}"
            )
        ready, _, _ = select.select([proc.stdout], [], [], 0.2)
        if ready:
            line = proc.stdout.readline()
            assert line.startswith("READY"), f"unexpected child output {line!r}"
            return proc, int(line.split()[1])
    proc.kill()
    raise AssertionError("repl child never became ready")


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_the_leader_chaos_soak(tmp_path):
    """Acceptance: SIGKILL the leader subprocess mid-workload, promote
    the follower, and prove the failover contract — zero acked-tx loss,
    zero duplicate commits, bounded failover time, and fencing that
    REFUSES the dead leader's epoch rather than merging it."""
    pp, drv, key, ident, rng = _client_kit(seed=0xC0FFEE)
    follower_wal = str(tmp_path / "follower.wal")
    leader_wal = str(tmp_path / "leader.wal")
    follower, fport = _spawn_repl_node(follower_wal, "follower")
    leader, lport = _spawn_repl_node(
        leader_wal, "leader", f"127.0.0.1:{fport}"
    )
    follower_addr = ("127.0.0.1", fport)
    client = RemoteNetwork(
        endpoints=[("127.0.0.1", lport), follower_addr],
        timeout=5, retries=12, backoff_s=0.05,
    )
    acked = []
    ack_times = []
    errors = []
    stop = threading.Event()

    def workload():
        k = 0
        while not stop.is_set():
            anchor = f"chaos-{k}"
            k += 1
            try:
                ev = client.submit(
                    _issue_bytes(drv, key, ident, rng, anchor)
                )
            except Exception as e:  # unacked: allowed to be lost
                errors.append(e)
                continue
            if ev.status != TxStatus.VALID:
                errors.append(AssertionError(f"rejected: {ev.message}"))
                stop.set()
                return
            acked.append(anchor)
            ack_times.append(time.monotonic())

    t = threading.Thread(target=workload, daemon=True)
    try:
        t.start()
        _wait(lambda: len(acked) >= 3, timeout=60,
              what="pre-kill acknowledged traffic")
        killed_at = time.monotonic()
        os.kill(leader.pid, signal.SIGKILL)
        leader.wait(timeout=30)
        # explicit operator failover: promote the follower over the wire
        promoter = RemoteNetwork(follower_addr, timeout=5, retries=5,
                                 backoff_s=0.1)
        epoch = promoter.promote()
        assert epoch >= 1
        pre_kill_acks = len(acked)
        _wait(lambda: len(acked) >= pre_kill_acks + 3, timeout=90,
              what="post-failover acknowledged traffic")
        stop.set()
        t.join(timeout=60)
        assert not t.is_alive()
        # rejected txs are contract violations; transport errors during
        # the failover window are expected and tolerated
        fatal = [e for e in errors if isinstance(e, AssertionError)]
        assert not fatal, fatal[0]
        # bounded failover: the first post-kill ack landed within budget
        post = [ts for ts in ack_times if ts > killed_at]
        assert post, "no acked tx after the kill"
        assert post[0] - killed_at < 60.0, (
            f"failover took {post[0] - killed_at:.1f}s"
        )
        # zero acked-tx loss on the promoted node
        for anchor in acked:
            ev = promoter.status(anchor)
            assert ev is not None and ev.status == TxStatus.VALID, anchor
        # fencing, live: the dead leader's epoch-0 appends are REFUSED
        resp = _raw_rpc(follower_addr, {
            "op": "repl.ship", "epoch": 0, "record": b"zombie".hex(),
        })
        assert resp["ok"] is False
        assert resp["error_class"] == "StaleEpoch"
        snap = promoter.ops_metrics()
        assert snap["counters"].get("repl.stale_rejected", 0) >= 1
        promoter.close()
    finally:
        stop.set()
        client.close()
        for proc in (leader, follower):
            if proc.poll() is None:
                proc.kill()
        follower.wait(timeout=30)
    # zero duplicate commits: recover the follower's journal in-process
    # and count every committed tx id across every block — this ALSO
    # exercises recovery of a follower-written WAL
    recovered = Network.recover(
        RequestValidator(FabTokenDriver(pp)), follower_wal
    )
    seen = {}
    for block in recovered._blocks:
        for txid in block.txs:
            seen[txid] = seen.get(txid, 0) + 1
    dups = {txid: n for txid, n in seen.items() if n > 1}
    assert not dups, f"tx ids committed twice across the failover: {dups}"
    # and every acked tx is present in the recovered ledger too
    for anchor in acked:
        assert recovered.status(anchor).status == TxStatus.VALID, anchor
