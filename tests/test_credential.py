"""Anonymous credential round trip: blind issuance + selective disclosure."""
import pytest

from fabric_token_sdk_tpu.crypto import credential as cr, hostmath as hm


def test_credential_lifecycle(rng):
    issuer = cr.CredentialIssuer.create(n_attrs=3, rng=rng)
    attrs = [21, 7, 1999]  # e.g. org unit, role, enrollment id
    user = cr.CredentialUser(issuer.public, attrs, rng)
    rec, req = user.request_credential()
    cred = user.finish(rec, issuer.blind_issue(req))

    verifier = cr.CredentialVerifier(issuer.public)
    # all-hidden presentation
    p1 = user.present(cred, b"login-challenge-1")
    assert verifier.verify(p1, b"login-challenge-1") == {}
    # selective disclosure of attribute 1
    p2 = user.present(cred, b"login-challenge-2", disclose=[1])
    assert verifier.verify(p2, b"login-challenge-2",
                           expect_disclosed={1: 7}) == {1: 7}
    # wrong expected disclosure
    with pytest.raises(ValueError):
        verifier.verify(p2, b"login-challenge-2", expect_disclosed={1: 8})
    # presentation is bound to the message
    with pytest.raises(ValueError):
        verifier.verify(p2, b"other-message")
    # lying about a disclosed value breaks the pairing equation
    from fabric_token_sdk_tpu.crypto.serialization import dumps, loads
    d = loads(p2)
    d["d"]["1"] = 8
    with pytest.raises(ValueError):
        verifier.verify(dumps(d), b"login-challenge-2")
    # unlinkability: two presentations differ (fresh randomization)
    p3 = user.present(cred, b"login-challenge-1")
    assert p3 != p1


def test_credential_forgery_rejected(rng):
    issuer = cr.CredentialIssuer.create(n_attrs=2, rng=rng)
    user = cr.CredentialUser(issuer.public, [5, 6], rng)
    rec, req = user.request_credential()
    cred = user.finish(rec, issuer.blind_issue(req))
    # present under a DIFFERENT issuer's key
    other = cr.CredentialIssuer.create(n_attrs=2, rng=rng)
    p = user.present(cred, b"m")
    with pytest.raises(ValueError):
        cr.CredentialVerifier(other.public).verify(p, b"m")
