"""Device-plane dispatch ledger (`utils/devobs.py`): zero-cost-when-off,
occupancy/waste arithmetic, per-program compile/cache attribution, and
the differential no-perturbation contract.

The ledger is an observer with the same contract as the host-path
profiler: ``FTS_DEVOBS=0`` must make every entry point an inert
passthrough (no ledger state, no registry writes, no threads), and on
or off the accept/reject verdicts of an identical workload must not
change.
"""
import random
import threading

import numpy as np
import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.ops import curve as cv, stages as st
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network, TxStatus
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import benchschema, devobs
from fabric_token_sdk_tpu.utils import metrics as mx


@pytest.fixture(autouse=True)
def _clean_ledger():
    """Each test sees (and leaves) a reset ledger; registry histograms
    are process-wide and asserted by delta only."""
    devobs.reset()
    yield
    devobs.reset()


# ===================================================================
# zero cost when off
# ===================================================================


def _hist_count(name):
    h = mx.REGISTRY.snapshot().get("histograms", {}).get(name)
    return h["count"] if h else 0


def test_off_is_passthrough(monkeypatch):
    monkeypatch.setenv("FTS_DEVOBS", "0")
    assert not devobs.enabled()
    agg_before = _hist_count("device.dispatch.seconds")
    threads_before = threading.active_count()
    with devobs.plane("verify"):
        with devobs.attribute("offtest_attr"):
            with devobs.dispatch("offtest_prog", rows=5, padded_rows=3):
                pass
    devobs.note_compile(1.0)
    devobs.note_cache("/jax/compilation_cache/cache_hits")
    devobs.note_degrade("offtest_reason")
    # no ledger state, no per-program registry metrics, no threads
    assert devobs.snapshot() == {}
    assert devobs.current_program() is None
    snap = mx.REGISTRY.snapshot()
    assert "device.dispatch.offtest_prog.seconds" not in snap.get(
        "histograms", {}
    )
    assert "device.offtest_prog.padded_rows" not in snap.get("counters", {})
    assert _hist_count("device.dispatch.seconds") == agg_before
    assert threading.active_count() == threads_before
    # off means off for the surfaced sections too
    assert devobs.health_section()["enabled"] is False
    assert devobs.health_section()["programs"] == {}


# ===================================================================
# ledger arithmetic + schema
# ===================================================================


def test_dispatch_records_occupancy_waste_and_placement():
    agg_before = _hist_count("device.dispatch.seconds")
    with devobs.plane("verify"):
        with devobs.dispatch("ledger_prog", rows=5, padded_rows=3, dp=2):
            pass
    snap = devobs.snapshot()
    assert set(snap) == {("verify", "ledger_prog")}
    e = snap[("verify", "ledger_prog")]
    assert e["dispatches"] == 1
    assert (e["rows"], e["padded_rows"], e["dp"], e["mp"]) == (5, 3, 2, 1)
    assert e["wall_s"] >= 0

    h = devobs.health_section()
    prog = h["programs"]["verify:ledger_prog"]
    assert prog["occupancy"] == 0.625
    assert prog["waste_frac"] == 0.375
    assert h["planes"]["verify"]["occupancy"] == 0.625

    # the registry got the histograms + the padding-waste counter
    assert _hist_count("device.dispatch.seconds") == agg_before + 1
    assert _hist_count("device.dispatch.ledger_prog.seconds") >= 1
    reg = mx.REGISTRY.snapshot()
    assert reg["counters"]["device.ledger_prog.padded_rows"] == 3

    # the bench `device` section validates against the shared schema
    section = devobs.section()
    assert benchschema.validate_device(section) == []
    assert section["dispatches"] == 1
    assert section["occupancy"] == 0.625
    assert section["waste_frac"] == 0.375


def test_compile_and_cache_attribution():
    with devobs.dispatch("attr_prog", rows=1):
        devobs.note_compile(0.25)
        devobs.note_cache("/jax/compilation_cache/cache_hits")
        devobs.note_cache("/jax/compilation_cache/cache_misses")
        assert devobs.current_program() == "attr_prog"
    # the frame outlives the block as the process-wide fallback (compiles
    # fired on sharding worker threads land on the last program)
    devobs.note_compile(0.25)
    e = devobs.snapshot()[(devobs.DEFAULT_PLANE, "attr_prog")]
    assert e["compiles"] == 2
    assert e["compile_s"] == pytest.approx(0.5)
    assert (e["cache_hits"], e["cache_misses"]) == (1, 1)

    # with no frame ever opened, events land on the unattributed bucket
    devobs.reset()
    devobs.note_compile(0.1)
    devobs.note_cache("/jax/compilation_cache/cache_hits")
    assert set(devobs.snapshot()) == {
        (devobs.DEFAULT_PLANE, devobs.UNATTRIBUTED)
    }

    # attribute() joins warmup's AOT loop to the ledger without faking a
    # dispatch
    devobs.reset()
    with devobs.attribute("warm_prog"):
        devobs.note_compile(0.2)
    e = devobs.snapshot()[(devobs.DEFAULT_PLANE, "warm_prog")]
    assert (e["dispatches"], e["compiles"]) == (0, 1)


def test_note_degrade_lands_on_named_program():
    devobs.note_degrade("k_not_divisible", program="fused_pairing")
    devobs.note_degrade("k_not_divisible", program="fused_pairing")
    e = devobs.snapshot()[(devobs.DEFAULT_PLANE, "fused_pairing")]
    assert e["degrades"] == {"k_not_divisible": 2}
    prog = devobs.health_section()["programs"]["stages:fused_pairing"]
    assert prog["degrades"] == 2
    assert prog["degrade_reasons"] == {"k_not_divisible": 2}


# ===================================================================
# a real staged dispatch lands in the ledger with the canonical name
# ===================================================================


def test_msm_dispatch_ledgered_with_canonical_program_name():
    rng = random.Random(0xD0B5)
    base = [hm.g1_mul(hm.G1_GEN, 3)]
    table = cv.FixedBaseTable(base)
    scalars = np.stack(
        [cv.encode_scalars([rng.randrange(hm.R)]) for _ in range(5)]
    )
    st.g1_msm_rows(table.flat, scalars)
    frame = ("stages", "g1_msm1_tile")
    e = devobs.snapshot()[frame]
    assert e["dispatches"] == 1
    assert e["rows"] == 5
    # run_rows pads the 5-row batch up to the ROW_TILE slab
    assert e["padded_rows"] == (-5) % st.ROW_TILE
    prog = devobs.health_section()["programs"]["stages:g1_msm1_tile"]
    assert prog["occupancy"] == pytest.approx(5 / (5 + (-5) % st.ROW_TILE))


# ===================================================================
# clamp-site attribution (satellite: _clamp_mp no longer drops `where`)
# ===================================================================


def test_clamp_site_is_attributed():
    from fabric_token_sdk_tpu.parallel import sharding

    before = mx.REGISTRY.snapshot().get("counters", {})
    cfg = sharding.MeshConfig.build(6, 4)
    assert cfg.mp == 3  # largest divisor of 6 that fits
    after = mx.REGISTRY.snapshot()["counters"]

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    # the aggregate stays (tests/test_parallel.py pins its delta), the
    # site now rides a per-site counter AND a reasoned flight event
    assert delta("sharding.clamped") == 1
    assert delta("sharding.clamped.meshconfig") == 1
    evt = [e for e in mx.FLIGHT.tail(50) if e["kind"] == "sharding.clamped"][-1]
    assert evt["where"] == "MeshConfig"
    assert (evt["want"], evt["got"], evt["n_devices"]) == (4, 3, 6)


# ===================================================================
# differential: the ledger never perturbs verdicts
# ===================================================================


def _run_scenario():
    """Deterministic mixed-verdict workload (the profiler's scenario):
    issue, then two transfers of which the second double-spends."""
    pp = FabTokenPublicParams()
    network = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(max_block_txs=8),
    )
    parties = {
        name: Party(name, FabTokenDriver(pp), network)
        for name in ("issuer-node", "alice-node", "bob-node")
    }
    parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet("alice", anonymous=False)
    bob = parties["bob-node"].new_owner_wallet("bob", anonymous=False)
    tx = Transaction(parties["issuer-node"], "devobs-seed")
    tx.issue("issuer", "USD", [5], [alice.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(None)
    tx.submit()
    alice_p = parties["alice-node"]
    tid = alice_p.vault.token_ids()[0]

    def spend(anchor):
        req = alice_p.tms.new_request(anchor)
        tokens, metas = alice_p.vault.get_many([tid])
        alice_p.tms.add_transfer(
            req, [tid], tokens, metas, "USD", [5],
            [bob.recipient_identity()],
        )
        alice_p.tms.sign_transfers(req)
        return req.to_bytes()

    events = network.submit_many([spend("dv-ok"), spend("dv-dup")])
    return [e.status for e in events]


def test_ledger_never_perturbs_verdicts(monkeypatch):
    monkeypatch.setenv("FTS_DEVOBS", "1")
    on_statuses = _run_scenario()
    assert on_statuses == [TxStatus.VALID, TxStatus.INVALID]
    monkeypatch.setenv("FTS_DEVOBS", "0")
    off_statuses = _run_scenario()
    assert off_statuses == on_statuses
