"""Batched device prover vs host prover/verifier (differential guarantee:
device proving may only accelerate, never change, accept/reject)."""
import random

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import batch, batch_prove, hostmath as hm
from fabric_token_sdk_tpu.crypto import token as tok, transfer as tr
from fabric_token_sdk_tpu.crypto import wellformedness as wf
from fabric_token_sdk_tpu.crypto.rangeproof import RangeProof
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.utils import metrics as mx


@pytest.fixture(scope="module")
def pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def _reqs(pp, rng, in_vals, out_vals, count):
    """Prove-request tuples (in_w, out_w, inputs, outputs), conservation
    respected by the caller's choice of values."""
    out = []
    for _ in range(count):
        in_toks, in_w = tok.tokens_with_witness(in_vals, "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness(out_vals, "USD", pp.ped_params, rng)
        out.append((in_w, out_w, in_toks, out_toks))
    return out


def _host_verify(pp, req, proof):
    tr.TransferVerifier(req[2], req[3], pp).verify(proof)


def test_batched_prove_accepted_by_host_and_batched_verifier(rng, pp):
    """1-in/1-out (range skipped): every device-produced proof verifies
    under the unchanged host verifier AND the batched verifier."""
    reqs = _reqs(pp, rng, [7], [7], 3)
    txs_before = mx.REGISTRY.counter("batch.prove.txs").value
    proofs = tr.TransferProver.batch(reqs, pp, rng=rng, min_batch=1)
    assert mx.REGISTRY.counter("batch.prove.txs").value - txs_before == 3
    for req, proof in zip(reqs, proofs):
        _host_verify(pp, req, proof)
    got = batch.BatchedTransferVerifier(pp).verify(
        [(r[2], r[3], p) for r, p in zip(reqs, proofs)]
    )
    assert got.tolist() == [True, True, True]


def test_batched_prove_tamper_rejected(rng, pp):
    """A bit-flipped device proof must be rejected by the host verifier
    and by the batched verifier — same accept/reject as host proofs."""
    reqs = _reqs(pp, rng, [9], [9], 2)
    proofs = tr.TransferProver.batch(reqs, pp, rng=rng, min_batch=1)
    tp = tr.TransferProof.from_bytes(proofs[0])
    bad_wf = wf.TransferWF.from_bytes(tp.wf)
    bad_wf.sum_resp = (bad_wf.sum_resp + 1) % hm.R
    tp.wf = bad_wf.to_bytes()
    bad = tp.to_bytes()
    with pytest.raises(ValueError):
        _host_verify(pp, reqs[0], bad)
    got = batch.BatchedTransferVerifier(pp).verify(
        [(reqs[0][2], reqs[0][3], bad), (reqs[1][2], reqs[1][3], proofs[1])]
    )
    assert got.tolist() == [False, True]


def test_empty_batch_returns_cleanly(pp):
    assert tr.TransferProver.batch([], pp) == []
    assert batch_prove.prover_for(pp).prove([]) == []


def test_below_min_batch_routes_host(rng, pp):
    """Groups smaller than min_batch never touch the device plane."""
    reqs = _reqs(pp, rng, [5], [5], 2)
    host_before = mx.REGISTRY.counter("batch.prove.host").value
    txs_before = mx.REGISTRY.counter("batch.prove.txs").value
    proofs = tr.TransferProver.batch(reqs, pp, rng=rng, min_batch=5)
    assert mx.REGISTRY.counter("batch.prove.host").value - host_before == 2
    assert mx.REGISTRY.counter("batch.prove.txs").value == txs_before
    for req, proof in zip(reqs, proofs):
        _host_verify(pp, req, proof)


def test_device_error_falls_back_to_host(rng, pp, monkeypatch):
    """Degrade-only contract: ANY device-plane failure yields host-proved
    (still valid) proofs and counts batch.prove.host_fallbacks."""

    class Boom:
        def prove(self, reqs, rng=None):
            raise MemoryError("injected device fault")

    monkeypatch.setattr(batch_prove, "prover_for", lambda pp: Boom())
    reqs = _reqs(pp, rng, [3], [3], 2)
    fall_before = mx.REGISTRY.counter("batch.prove.host_fallbacks").value
    proofs = tr.TransferProver.batch(reqs, pp, rng=rng, min_batch=1)
    assert (
        mx.REGISTRY.counter("batch.prove.host_fallbacks").value - fall_before
        == 2
    )
    for req, proof in zip(reqs, proofs):
        _host_verify(pp, req, proof)


def test_mixed_shapes_return_in_request_order(rng, pp):
    """batch() groups by shape internally; results come back in request
    order. The odd-shaped singleton (below min_batch) takes the host
    prover, the uniform group rides the device plane."""
    device = _reqs(pp, rng, [4], [4], 2)
    odd = _reqs(pp, rng, [5, 10], [7, 8], 1)
    reqs = [device[0], odd[0], device[1]]
    host_before = mx.REGISTRY.counter("batch.prove.host").value
    txs_before = mx.REGISTRY.counter("batch.prove.txs").value
    proofs = tr.TransferProver.batch(reqs, pp, rng=rng, min_batch=2)
    assert mx.REGISTRY.counter("batch.prove.host").value - host_before == 1
    assert mx.REGISTRY.counter("batch.prove.txs").value - txs_before == 2
    for req, proof in zip(reqs, proofs):
        _host_verify(pp, req, proof)


def test_uniform_shape_required_by_device_prover(rng, pp):
    """The raw BatchedTransferProver rejects mixed shapes (batch() is the
    router that handles grouping)."""
    reqs = _reqs(pp, rng, [4], [4], 1) + _reqs(pp, rng, [5, 5], [6, 4], 1)
    with pytest.raises(ValueError, match="uniform"):
        batch_prove.prover_for(pp).prove(reqs)


@pytest.mark.slow
def test_batched_prove_full_range_differential(rng, pp):
    """2-in/2-out: the full WF + range + membership device prove path.
    Every proof accepted by host AND batched verifiers; a tampered
    membership response is rejected by both."""
    reqs = _reqs(pp, rng, [5, 10], [7, 8], 3)
    prover = batch_prove.prover_for(pp)
    proofs = prover.prove(reqs, rng)
    for req, proof in zip(reqs, proofs):
        _host_verify(pp, req, proof)
    bv = batch.BatchedTransferVerifier(pp)
    got = bv.verify([(r[2], r[3], p) for r, p in zip(reqs, proofs)])
    assert got.tolist() == [True, True, True]

    tp = tr.TransferProof.from_bytes(proofs[1])
    rpf = RangeProof.from_bytes(tp.range_correctness)
    rpf.membership_proofs[0][0].value_resp = (
        rpf.membership_proofs[0][0].value_resp + 1
    ) % hm.R
    tp.range_correctness = rpf.to_bytes()
    bad = tp.to_bytes()
    with pytest.raises(ValueError):
        _host_verify(pp, reqs[1], bad)
    got = bv.verify(
        [(reqs[1][2], reqs[1][3], bad), (reqs[0][2], reqs[0][3], proofs[0])]
    )
    assert got.tolist() == [False, True]


@pytest.mark.slow
def test_transfer_many_driver_spi(rng, pp):
    """driver.transfer_many proofs validate through the unchanged
    validate_transfer host path (2-in/2-out incl. range)."""
    from fabric_token_sdk_tpu.crypto import sign
    from fabric_token_sdk_tpu.drivers import identity
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
    from fabric_token_sdk_tpu.models.token import ID

    driver = ZKATDLogDriver(pp)
    key = sign.keygen(rng)
    ident = identity.pk_identity(key.public)
    outcome = driver.issue(
        ident, "USD", [100, 55] * 2, [ident] * 4, anonymous=True, rng=rng
    )
    resolve = {ID("iss", i): outcome.outputs[i] for i in range(4)}
    specs = [
        (
            [ID("iss", 2 * i), ID("iss", 2 * i + 1)],
            outcome.outputs[2 * i : 2 * i + 2],
            outcome.metadata[2 * i : 2 * i + 2],
            "USD", [120, 35], [ident, ident],
        )
        for i in range(2)
    ]
    touts = driver.transfer_many(specs, rng=rng)
    sig = [key.sign(b"payload", rng), key.sign(b"payload", rng)]
    for tout in touts:
        driver.validate_transfer(
            tout.action_bytes, lambda x: resolve[x], b"payload", sig
        )
