"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is unavailable in CI; all sharding tests run on a
virtual CPU mesh (`--xla_force_host_platform_device_count=8`). Kernels are
written for TPU; CPU execution exercises identical XLA programs.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import random

import pytest


@pytest.fixture
def rng():
    return random.Random(0xF75)
