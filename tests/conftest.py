"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is unavailable in CI; all sharding tests run on a
virtual CPU mesh (`--xla_force_host_platform_device_count=8`). Kernels are
written for TPU; CPU execution exercises identical XLA programs.
"""
import os
import sys

# The package ships without an installer; the repo root on sys.path is
# what makes `fabric_token_sdk_tpu` (and `import __graft_entry__`)
# importable from any pytest invocation directory.
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Hard-force CPU: the ambient environment pins JAX_PLATFORMS=axon (remote
# TPU tunnel via the sitecustomize in /root/.axon_site, triggered by
# PALLAS_AXON_POOL_IPS). The axon PJRT client is registered at interpreter
# startup and hangs every jax call when the tunnel is down — too late to
# undo from here. The re-exec-once-with-a-cleaned-env logic lives in
# __graft_entry__.neutralize_axon (shared with the standalone driver
# entry points); tests pass the pytest re-exec argv explicitly. bench.py
# is the only entry point that targets the real chip.
import __graft_entry__ as _graft

_graft.neutralize_axon(["-m", "pytest"] + sys.argv[1:])

os.environ["JAX_PLATFORMS"] = "cpu"  # tests hard-force CPU, always
_graft.ensure_virtual_devices(8)  # sharding tests need the virtual mesh

# Persistent XLA compilation cache is configured centrally in
# fabric_token_sdk_tpu/ops/__init__.py (~/.cache/fts_tpu_jax); kernels are
# row-tiled (crypto/batch.py ROW_TILE) and setup fixtures seeded so cache
# entries hit across runs.

import random

import pytest


@pytest.fixture(scope="session", autouse=True)
def fts_warmup_session():
    """Opt-in session warmup: `FTS_WARMUP=1 pytest ...` AOT-compiles the
    whole canonical stage/pairing program set up front (populating the
    persistent cache), so no test ever pays a surprise giant compile
    mid-session. `FTS_WARMUP_PAIRING=0` skips the large pairing tiles."""
    if os.environ.get("FTS_WARMUP") == "1":
        from fabric_token_sdk_tpu.ops import warmup as wu

        wu.warmup(
            include_pairing=os.environ.get("FTS_WARMUP_PAIRING", "1") == "1"
        )
    yield


@pytest.fixture
def rng():
    return random.Random(0xF75)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No fault armed in one test may leak into the next (the fault
    registry is process-global by design — see utils/faults.py), and no
    tripped circuit breaker may reject the next test's device dispatch
    (the breaker registry is process-global too)."""
    yield
    from fabric_token_sdk_tpu.utils import faults, resilience

    if faults.armed():
        faults.clear()
    resilience.reset()
