"""CI drift check: the metric taxonomy in docs/OBSERVABILITY.md cannot
rot. Every counter/gauge/histogram/span/flight-event name emitted by the
codebase must appear in the doc, and every metric-shaped name the doc
claims must still exist in the code — so removed metrics get pruned and
new metrics get documented in the same PR that touches them.
"""

import os
import re

REPO = os.path.join(os.path.dirname(__file__), "..")
DOC_PATH = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# sources that emit metrics (tests excluded: scratch names are fine there)
SRC_DIRS = ("fabric_token_sdk_tpu", "cmd")
SRC_FILES = ("bench.py", "__graft_entry__.py")

# literal first-arg instrument/span/flight call sites; f-strings keep
# their "{placeholder}" tail, normalized to a prefix below
_PATTERNS = (
    ("counter", re.compile(r'\.counter\(\s*f?"([^"]+)"')),
    ("gauge", re.compile(r'\.gauge\(\s*f?"([^"]+)"')),
    ("histogram", re.compile(r'\.histogram\(\s*f?"([^"]+)"')),
    ("histogram", re.compile(r'\.timed\(\s*f?"([^"]+)"')),
    ("span", re.compile(r'\.span\(\s*f?"([^"]+)"')),
    ("span", re.compile(r'\.record_span\(\s*f?"([^"]+)"')),
    ("span", re.compile(r'_spanned\(\s*f?"([^"]+)"')),
    ("flight", re.compile(r'\.flight\(\s*f?"([^"]+)"')),
    ("flight", re.compile(r'FLIGHT\.record\(\s*f?"([^"]+)"')),
)

# doc tokens that look metric-shaped but are file/module references
_DOC_SKIP_SUFFIXES = (".py", ".pyc", ".c", ".cc", ".md", ".json", ".go")
_DOC_SKIP = {"jax.monitoring"}


def _source_files():
    for d in SRC_DIRS:
        for root, _dirs, files in os.walk(os.path.join(REPO, d)):
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(root, f)
    for f in SRC_FILES:
        yield os.path.join(REPO, f)


def _emitted():
    """{(kind, name)}: every literal metric/span/flight name in the code.
    f-string names are cut at the first '{' and marked as prefixes by
    their trailing '.'."""
    out = set()
    corpus = []
    for path in _source_files():
        with open(path) as fh:
            text = fh.read()
        corpus.append(text)
        for kind, pat in _PATTERNS:
            for name in pat.findall(text):
                out.add((kind, name.split("{")[0]))
    return out, "\n".join(corpus)


def _expand_doc_token(token):
    """Expand one backticked doc token into concrete names: `{a,b}`
    groups, trailing `x/y/z` and `x|y` alternations over the last dotted
    segment. Tokens containing `<placeholder>` become prefixes (cut at
    '<')."""
    m = re.search(r"\{([^}]*,[^}]*)\}", token)
    if m:
        out = []
        for alt in m.group(1).split(","):
            out.extend(_expand_doc_token(token[: m.start()] + alt + token[m.end():]))
        return out
    names = [token]
    for sep in ("/", "|"):
        new = []
        for t in names:
            if sep in t:
                parts = t.split(sep)
                head = parts[0]
                prefix = head.rsplit(".", 1)[0] + "." if "." in head else ""
                new.append(head)
                new.extend(prefix + p for p in parts[1:])
            else:
                new.append(t)
        names = new
    return names


_METRIC_SHAPE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+\.?$")


def _doc_names(doc_text):
    """(exact names, prefix names) the doc claims, from backticked
    metric-shaped tokens."""
    exact, prefixes = set(), set()
    for token in re.findall(r"`([^`\n]+)`", doc_text):
        if token.startswith("<"):
            continue
        if token.endswith(_DOC_SKIP_SUFFIXES) or token in _DOC_SKIP:
            continue
        for name in _expand_doc_token(token):
            cut = name.split("<")[0]
            is_prefix = cut != name or name.endswith(".")
            cut = cut.rstrip(".") + ("." if is_prefix else "")
            if not _METRIC_SHAPE.match(cut.rstrip(".") + (".x" if is_prefix else "")):
                if not (is_prefix and _METRIC_SHAPE.match(cut + "x")):
                    continue
            (prefixes if is_prefix else exact).add(cut)
    return exact, prefixes


def _doc_flight_kinds(doc_text):
    """Event kinds claimed by the flight-recorder taxonomy table (first
    column of each row, between the section heading and the next one)."""
    m = re.search(
        r"## Flight-recorder event taxonomy(.*?)\n## ", doc_text, re.S
    )
    assert m, "docs/OBSERVABILITY.md lost its flight-recorder taxonomy section"
    return set(
        re.findall(r"^\|\s*`([a-z][a-z0-9_.]*)`\s*\|", m.group(1), re.M)
    )


def test_every_emitted_metric_is_documented():
    emitted, _corpus = _emitted()
    with open(DOC_PATH) as fh:
        doc = fh.read()
    doc_flight = _doc_flight_kinds(doc)
    exact, prefixes = _doc_names(doc)

    def documented(name):
        if name.endswith("."):
            # emitted prefix (f-string name): any doc prefix or exact
            # name under it counts as documentation
            return any(
                d.startswith(name) or name.startswith(d) for d in prefixes
            ) or any(d.startswith(name) for d in exact)
        if name in exact:
            return True
        return any(name.startswith(p) for p in prefixes)

    missing = []
    for kind, name in sorted(emitted):
        base = name.rstrip(".") if not name.endswith(".") else name
        if kind == "flight":
            if base not in doc_flight:
                missing.append(f"flight event `{base}`")
            continue
        # spans are documented either by span name or their auto-fed
        # `<name>.seconds` histogram
        needles = [base]
        if kind == "span":
            needles.append(base + ".seconds")
        if not any(documented(n) for n in needles):
            missing.append(f"{kind} `{base}`")
    assert not missing, (
        "metric names emitted but absent from docs/OBSERVABILITY.md "
        "(document them in the taxonomy):\n  " + "\n  ".join(missing)
    )


def test_profiler_and_slo_names_pinned_both_ways():
    """The observability-PR names cannot drift in either direction: the
    host sub-leg histograms, the sampler counters, the SLO gauges and
    the `slo.breach` flight kind must be emitted by the code AND
    documented; the `FTS_PROF_*`/`FTS_SLO_*` env knobs referenced by the
    code must appear in the doc's switches table and vice versa."""
    from fabric_token_sdk_tpu.utils import profiler

    emitted, corpus = _emitted()
    emitted_names = {name for _kind, name in emitted}
    with open(DOC_PATH) as fh:
        doc = fh.read()
    exact, prefixes = _doc_names(doc)

    # sub-leg histograms: emitted as the f-string prefix `ledger.host.`,
    # documented as the five concrete `ledger.host.<leg>.seconds` names
    assert ("histogram", "ledger.host.") in emitted
    assert set(profiler.LEGS) == {
        "unmarshal", "fiat_shamir", "sig_verify", "conservation",
        "input_match",
    }
    for leg in profiler.LEGS:
        assert f"ledger.host.{leg}.seconds" in exact, leg

    # sampler + SLO instruments, both ways
    for name in ("prof.samples", "prof.dropped", "prof.errors",
                 "prof.stacks", "slo.breaches"):
        assert name in emitted_names, f"{name} no longer emitted"
        assert name in exact, f"{name} undocumented"
    for prefix in ("slo.burn.", "slo.budget."):
        assert prefix in emitted_names, f"{prefix}* no longer emitted"
        assert prefix in prefixes, f"{prefix}* undocumented"

    # the breach flight kind rides the taxonomy table
    assert ("flight", "slo.breach") in emitted
    assert "slo.breach" in _doc_flight_kinds(doc)

    # exemplar meta key: published by the engine, named in the doc
    assert '"slo.exemplars"' in corpus
    assert "`slo.exemplars`" in doc

    # env knobs both ways: every FTS_PROF_*/FTS_SLO_* the code reads is
    # in the switches table, and the table names no dead knobs
    code_knobs = set(re.findall(r'"(FTS_(?:PROF|SLO)_[A-Z0-9_]+)"', corpus))
    doc_knobs = set(re.findall(r"`(FTS_(?:PROF|SLO)_[A-Z0-9_]+)`", doc))
    assert code_knobs, "no FTS_PROF_*/FTS_SLO_* knobs found (parser drift?)"
    assert code_knobs - doc_knobs == set(), (
        f"env knobs missing from the doc: {sorted(code_knobs - doc_knobs)}"
    )
    assert doc_knobs - code_knobs == set(), (
        f"doc names knobs the code no longer reads: "
        f"{sorted(doc_knobs - code_knobs)}"
    )


def test_device_ledger_names_pinned_both_ways():
    """The dispatch-ledger PR's names cannot drift in either direction:
    the aggregate + per-program dispatch histograms, the per-plane
    occupancy histogram, the padding-waste counter, the clamp-site
    counters and the degrade flight kinds must be emitted by the code
    AND documented; the `FTS_DEVOBS` switch the code reads must appear
    in the doc's switches table."""
    emitted, corpus = _emitted()
    emitted_names = {name for _kind, name in emitted}
    with open(DOC_PATH) as fh:
        doc = fh.read()
    exact, prefixes = _doc_names(doc)

    # aggregate dispatch histogram: exact name, both ways
    assert ("histogram", "device.dispatch.seconds") in emitted
    assert "device.dispatch.seconds" in exact

    # f-string families: emitted as prefixes, documented as
    # `<placeholder>`-style prefixes
    for prefix in ("device.dispatch.", "device."):
        assert prefix in emitted_names, f"{prefix}* no longer emitted"
        assert prefix in prefixes, f"{prefix}* undocumented"
    for token in ("device.dispatch.<program>.seconds",
                  "device.<plane>.occupancy",
                  "device.<program>.padded_rows",
                  "sharding.clamped.<where>"):
        assert f"`{token}`" in doc, f"{token} undocumented"

    # clamp-site counter family + breaker-skip counter, both ways
    assert ("counter", "sharding.clamped.") in emitted
    assert "sharding.clamped." in prefixes
    assert ("counter", "sharding.breaker_skips") in emitted
    assert "sharding.breaker_skips" in exact

    # degrade decisions are reasoned flight events, in the taxonomy
    doc_flight = _doc_flight_kinds(doc)
    for kind in ("sharding.fallback", "sharding.clamped"):
        assert ("flight", kind) in emitted, f"{kind} no longer emitted"
        assert kind in doc_flight, f"{kind} missing from flight taxonomy"

    # the ledger switch, both ways
    assert '"FTS_DEVOBS"' in corpus, "code no longer reads FTS_DEVOBS"
    assert "`FTS_DEVOBS`" in doc, "FTS_DEVOBS missing from switches table"


def test_host_batch_names_pinned_both_ways():
    """The batch-first host-validation PR's names cannot drift in
    either direction: the proved-row counters, the request/parse cache
    counters, the per-pass block histograms, the multiexp path
    counters, the host-batch flight kinds, and the four switches the
    code reads must be emitted by the code AND documented."""
    emitted, corpus = _emitted()
    with open(DOC_PATH) as fh:
        doc = fh.read()
    exact, _prefixes = _doc_names(doc)

    counters = (
        "hostbatch.sign.rows",
        "hostbatch.proof.rows",
        "hostbatch.conservation.rows",
        "request.cache.hits",
        "request.cache.misses",
        "request.cache.evictions",
        "parse.cache.hits",
        "parse.cache.misses",
        "hostmath.g1_multiexp_rows.native",
        "hostmath.g1_multiexp_rows.python",
    )
    for name in counters:
        assert ("counter", name) in emitted, f"{name} no longer emitted"
        assert name in exact, f"{name} undocumented"

    for name in (
        "ledger.block.host_sign_batch.seconds",
        "ledger.block.host_proof_batch.seconds",
        "ledger.block.host_conservation.seconds",
    ):
        assert ("histogram", name) in emitted, f"{name} no longer emitted"
        assert name in exact, f"{name} undocumented"

    doc_flight = _doc_flight_kinds(doc)
    for kind in ("sign.host_batch", "verify.host_batch",
                 "request.cache.evict"):
        assert ("flight", kind) in emitted, f"{kind} no longer emitted"
        assert kind in doc_flight, f"{kind} missing from flight taxonomy"

    for knob in ("FTS_HOST_BATCH", "FTS_COMMIT_WORKERS",
                 "FTS_REQUEST_CACHE", "FTS_PARSE_CACHE"):
        assert f'"{knob}"' in corpus, f"code no longer reads {knob}"
        assert f"`{knob}`" in doc, f"{knob} missing from switches table"


def test_replication_names_pinned_both_ways():
    """The replicated-ledger-plane PR's names cannot drift in either
    direction: the shipping/apply/bootstrap counters, the fencing and
    role-change counters, the client-failover counters, the ship-wait
    histogram, the replication flight kinds, and the switches the code
    reads must be emitted by the code AND documented."""
    emitted, corpus = _emitted()
    with open(DOC_PATH) as fh:
        doc = fh.read()
    exact, _prefixes = _doc_names(doc)

    counters = (
        "repl.shipped.records",
        "repl.ship.dropped",
        "repl.ship.ack_timeouts",
        "repl.ship.unsynced",
        "repl.applied.records",
        "repl.apply.skipped",
        "repl.bootstraps",
        "repl.bootstraps.sent",
        "repl.heartbeats",
        "repl.promotions",
        "repl.demotions",
        "repl.stale_rejected",
        "repl.link.errors",
        "repl.link.node_stopped",
        "remote.dispatch.not_leader",
        "remote.failover.switches",
    )
    for name in counters:
        assert ("counter", name) in emitted, f"{name} no longer emitted"
        assert name in exact, f"{name} undocumented"

    name = "repl.ship.wait.seconds"
    assert ("histogram", name) in emitted, f"{name} no longer emitted"
    assert name in exact, f"{name} undocumented"

    doc_flight = _doc_flight_kinds(doc)
    for kind in ("repl.bootstrap", "repl.promote", "repl.demoted",
                 "repl.fenced", "repl.link.stopped", "repl.ship.drop",
                 "failover"):
        assert ("flight", kind) in emitted, f"{kind} no longer emitted"
        assert kind in doc_flight, f"{kind} missing from flight taxonomy"

    for knob in ("FTS_REPL", "FTS_REPL_SHIP_TIMEOUT_S",
                 "FTS_REPL_QUEUE_MAX", "FTS_REPL_HEARTBEAT_S",
                 "FTS_REPL_LEASE_S", "FTS_REPL_AUTO_PROMOTE",
                 "FTS_REMOTE_ENDPOINTS", "FTS_BENCH_SOAK_FAILOVER"):
        assert f'"{knob}"' in corpus, f"code no longer reads {knob}"
        assert f"`{knob}`" in doc, f"{knob} missing from switches table"


def _wire_ops():
    """Every RPC op name `LedgerServer._dispatch_op` handles (the live
    wire protocol, ops plane included)."""
    path = os.path.join(
        REPO, "fabric_token_sdk_tpu", "services", "network", "remote.py"
    )
    with open(path) as fh:
        text = fh.read()
    ops = set(re.findall(r'op == "([a-z_.]+)"', text))
    assert ops, "no dispatch ops found in remote.py (parser drift?)"
    return ops


def _doc_rpc_ops(doc_text):
    """Op names claimed by the RPC catalog table in the Live ops plane
    section (first column of each row)."""
    m = re.search(r"### RPC catalog(.*?)\n###? ", doc_text, re.S)
    assert m, "docs/OBSERVABILITY.md lost its RPC catalog section"
    return set(re.findall(r"^\|\s*`([a-z_.]+)`\s*\|", m.group(1), re.M))


def test_rpc_catalog_matches_dispatch():
    """The Live ops plane RPC catalog cannot rot: every wire op the
    server dispatches is documented, and every documented op is still
    dispatched."""
    with open(DOC_PATH) as fh:
        doc = fh.read()
    code_ops, doc_ops = _wire_ops(), _doc_rpc_ops(doc)
    assert code_ops - doc_ops == set(), (
        f"wire ops missing from the RPC catalog: {sorted(code_ops - doc_ops)}"
    )
    assert doc_ops - code_ops == set(), (
        f"RPC catalog documents ops no longer dispatched: "
        f"{sorted(doc_ops - code_ops)}"
    )


def test_quantile_suffixes_and_memory_gauges_documented():
    """The quantile export (histogram `p50`/`p95`/`p99` keys and the
    Prometheus companion series) and the memory-telemetry gauge families
    (`stages.mem.*`, `proc.rss.*`) must be documented."""
    from fabric_token_sdk_tpu.utils import metrics

    with open(DOC_PATH) as fh:
        doc = fh.read()
    labels = [label for label, _q in metrics.QUANTILES]
    assert labels == ["p50", "p95", "p99"]
    for label in labels:
        assert f"`{label}`" in doc, f"quantile suffix {label} undocumented"
    # the quantile keys must actually exist in a snapshot
    h = metrics.Histogram("doccheck", buckets=(1.0,))
    h.observe(0.5)
    snap = h.snapshot()
    for label in labels:
        assert label in snap
    for needle in ("stages.mem.high_water.bytes", "stages.mem.device.bytes",
                   "proc.rss.bytes", "proc.rss.peak.bytes",
                   "device.mem.bytes", "orderer.queue.depth",
                   "ledger.inflight"):
        assert f"`{needle}`" in doc, f"ops-plane gauge {needle} undocumented"


def test_every_documented_metric_still_exists():
    emitted, corpus = _emitted()
    emitted_names = {name for _kind, name in emitted}
    emitted_exact = {n for n in emitted_names if not n.endswith(".")}
    emitted_prefixes = {n for n in emitted_names if n.endswith(".")}
    # span names also exist as `<name>.seconds` histograms
    for kind, name in list(emitted):
        if kind == "span" and not name.endswith("."):
            emitted_exact.add(name + ".seconds")
    with open(DOC_PATH) as fh:
        doc = fh.read()
    exact, prefixes = _doc_names(doc)
    exact |= _doc_flight_kinds(doc)

    def exists(name):
        base = name.rstrip(".")
        if base in emitted_exact or name in emitted_prefixes:
            return True
        if any(base.startswith(p) for p in emitted_prefixes):
            return True
        if name.endswith(".") and any(
            e.startswith(name) for e in emitted_exact
        ):
            return True
        # dynamically-built names (the jax.* monitoring plane) must at
        # least appear verbatim somewhere in the source tree
        return base in corpus

    stale = sorted(n for n in exact | prefixes if not exists(n))
    assert not stale, (
        "docs/OBSERVABILITY.md documents metrics no longer emitted "
        "anywhere (prune or fix them):\n  " + "\n  ".join(stale)
    )
