"""Integration: fungible token flows over the services runtime.

Mirrors reference `integration/token/fungible` suites: issue, audited
transfers, redeem, double spend rejection, insufficient funds, concurrent
transfers with the selector, history/balances, certification.
"""
import random
import threading

import pytest

from fabric_token_sdk_tpu.api.driver import ValidationError
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.auditor import AuditorService
from fabric_token_sdk_tpu.services.certifier import CertificationService
from fabric_token_sdk_tpu.services.network import Network, TxStatus
from fabric_token_sdk_tpu.services.owner import OwnerService
from fabric_token_sdk_tpu.services.query import QueryService
from fabric_token_sdk_tpu.services.selector import InsufficientFunds
from fabric_token_sdk_tpu.services.ttx import Party, Transaction


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))  # max 15 per token


def build_env(driver_factory, nym_params=None):
    """One auditor + issuer party + alice + bob on a shared network."""
    from fabric_token_sdk_tpu.api.wallet import AuditorWallet
    from fabric_token_sdk_tpu.crypto import sign

    aw = AuditorWallet("auditor", sign.keygen())
    auditor_svc = AuditorService(driver_factory(), aw)
    validator_driver = driver_factory()
    network = Network(RequestValidator(validator_driver, aw.identity))
    network.subscribe(auditor_svc.on_finality)

    parties = {}
    for name in ("issuer-node", "alice-node", "bob-node"):
        parties[name] = Party(name, driver_factory(), network,
                              auditor_identity=aw.identity)
    issuer = parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet(
        "alice", anonymous=nym_params is not None, nym_params=nym_params)
    bob = parties["bob-node"].new_owner_wallet(
        "bob", anonymous=nym_params is not None, nym_params=nym_params)
    if hasattr(validator_driver, "pp") and hasattr(validator_driver.pp, "add_issuer"):
        validator_driver.pp.add_issuer(issuer.identity)
    return network, auditor_svc, parties, issuer, alice, bob


def fungible_suite(network, auditor_svc, parties, issuer, alice, bob, max_value):
    issuer_p, alice_p, bob_p = (
        parties["issuer-node"], parties["alice-node"], parties["bob-node"])

    # issue two tokens to alice (10 + 5)
    tx = Transaction(issuer_p, "tx-issue-1")
    tx.issue("issuer", "USD", [10, 5],
             [alice.recipient_identity(), alice.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(auditor_svc)
    tx.submit()
    assert alice_p.balance("USD") == 15
    assert bob_p.balance("USD") == 0

    # alice pays bob 7 (change 8 back to alice)
    tx2 = Transaction(alice_p, "tx-pay-1")
    tx2.transfer("alice", "USD", [7], [bob.recipient_identity()])
    tx2.collect_endorsements(auditor_svc)
    tx2.submit()
    assert bob_p.balance("USD") == 7
    assert alice_p.balance("USD") == 8

    # bob redeems 4
    tx3 = Transaction(bob_p, "tx-redeem-1")
    tx3.redeem("bob", "USD", 4)
    tx3.collect_endorsements(auditor_svc)
    tx3.submit()
    assert bob_p.balance("USD") == 3

    # insufficient funds
    tx4 = Transaction(alice_p, "tx-too-much")
    with pytest.raises(InsufficientFunds):
        tx4.transfer("alice", "USD", [100], [bob.recipient_identity()])

    # double spend: replay an already-committed request
    replay = network.submit(tx2.request.to_bytes())
    assert replay.status == TxStatus.VALID  # idempotent same tx id
    # craft a new tx spending the same (now spent) inputs
    import dataclasses
    req = tx2.request
    req2 = dataclasses.replace(req, anchor="tx-replay")
    evil = network.submit(req2.to_bytes())
    # rejected: the auditor signature binds the anchor, and even with a
    # fresh audit the inputs are spent
    assert evil.status == TxStatus.INVALID
    req3 = dataclasses.replace(req, anchor="tx-replay-2")
    auditor_svc.audit(req3)  # re-audited replay still hits MVCC
    evil2 = network.submit(req3.to_bytes())
    assert evil2.status == TxStatus.INVALID
    assert "spent" in evil2.message or "exist" in evil2.message

    # history + holdings on the owner service
    owner_view = OwnerService(alice_p.db)
    assert owner_view.transaction_status("tx-pay-1") == "Confirmed"
    assert owner_view.payments("alice", "USD") == 7
    q = QueryService(bob_p.vault)
    assert q.balances_by_type() == {"USD": 3}

    # certification
    cert_svc = CertificationService(network)
    bob_ids = bob_p.vault.token_ids()
    cert_svc.certify_into(bob_p.vault, bob_ids[0])
    assert bob_p.vault.certification(bob_ids[0]) is not None
    with pytest.raises(ValidationError):
        cert_svc.certify(ID("tx-issue-1", 0))  # spent token

    # auditor saw everything, including the redeem's full (burn+change) amount
    assert auditor_svc.db.status("tx-pay-1") == "Confirmed"
    assert auditor_svc.db.status("tx-redeem-1") == "Confirmed"
    redeem_rec = [r for r in auditor_svc.db.transactions()
                  if r.tx_id == "tx-redeem-1"][0]
    assert redeem_rec.amount == 7  # 4 burned + 3 change, all audited

    # issuing above the driver's max value must fail before reaching the ledger
    tx_over = Transaction(parties["issuer-node"], "tx-over")
    with pytest.raises(ValueError):
        tx_over.issue("issuer", "USD", [max_value + 1],
                      [alice.recipient_identity()], anonymous=False)


def test_fabtoken_fungible_suite():
    def mk():
        return FabTokenDriver(FabTokenPublicParams())
    network, auditor_svc, parties, issuer, alice, bob = build_env(mk)
    fungible_suite(network, auditor_svc, parties, issuer, alice, bob,
                   max_value=(1 << 64) - 1)


def test_zkatdlog_fungible_suite(zk_pp):
    def mk():
        return ZKATDLogDriver(zk_pp)
    network, auditor_svc, parties, issuer, alice, bob = build_env(
        mk, nym_params=zk_pp.nym_params)
    fungible_suite(network, auditor_svc, parties, issuer, alice, bob,
                   max_value=zk_pp.max_token_value())


def test_concurrent_transfers_selector():
    """Two threads transferring from the same wallet must not double-select."""
    def mk():
        return FabTokenDriver(FabTokenPublicParams())
    network, auditor_svc, parties, issuer, alice, bob = build_env(mk)
    issuer_p, alice_p, bob_p = (
        parties["issuer-node"], parties["alice-node"], parties["bob-node"])
    tx = Transaction(issuer_p, "seed")
    tx.issue("issuer", "USD", [6, 6],
             [alice.recipient_identity(), alice.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(auditor_svc)
    tx.submit()

    results = []

    def worker(n):
        t = Transaction(alice_p, f"c-{n}")
        try:
            t.transfer("alice", "USD", [6], [bob.recipient_identity()])
            t.collect_endorsements(auditor_svc)
            t.submit()
            results.append(("ok", n))
        except Exception as e:
            results.append(("err", type(e).__name__))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(r[0] for r in results) == ["ok", "ok"]  # both succeed (6+6)
    assert bob_p.balance("USD") == 12
    assert alice_p.balance("USD") == 0
