"""Differential tests: batched G1 kernels vs host curve math."""
import jax.numpy as jnp
import numpy as np

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.ops import curve as cv


def _host_pts(rng, n):
    return [hm.rand_g1(rng) for _ in range(n)]


def test_point_roundtrip(rng):
    pts = _host_pts(rng, 4) + [None]
    assert cv.decode_points(cv.encode_points(pts)) == pts


def test_double_add_matches_host(rng):
    pts = _host_pts(rng, 4)
    P = cv.encode_points(pts)
    assert cv.decode_points(cv.double(P)) == [hm.g1_double(p) for p in pts]
    qs = _host_pts(rng, 4)
    Q = cv.encode_points(qs)
    assert cv.decode_points(cv.add(P, Q)) == [hm.g1_add(p, q) for p, q in zip(pts, qs)]


def test_add_edge_cases(rng):
    p = _host_pts(rng, 1)[0]
    P = cv.encode_points([p, p, p, None, None])
    Q = cv.encode_points([p, hm.g1_neg(p), None, p, None])
    got = cv.decode_points(cv.add(P, Q))
    assert got == [hm.g1_double(p), None, p, p, None]


def test_eq(rng):
    p, q = _host_pts(rng, 2)
    # same point with different Z (scale Jacobian coords)
    P = cv.encode_points([p, p, None, p])
    P2 = cv.double(cv.encode_points([p, q, None, None]))
    Pd = cv.encode_points([hm.g1_double(p), hm.g1_double(q), None, None])
    assert np.asarray(cv.eq(P2, Pd)).tolist() == [True, True, True, True]
    # point!=point, point==point, inf vs point, point vs inf
    assert np.asarray(cv.eq(P, cv.encode_points([q, p, p, None]))).tolist() == [
        False,
        True,
        False,
        False,
    ]


def test_scalar_mul_matches_host(rng):
    pts = _host_pts(rng, 3)
    ks = [rng.randrange(hm.R) for _ in range(3)]
    got = cv.decode_points(cv.scalar_mul(cv.encode_points(pts), cv.encode_scalars(ks)))
    assert got == [hm.g1_mul(p, k) for p, k in zip(pts, ks)]


def test_scalar_mul_edges(rng):
    p = _host_pts(rng, 1)[0]
    P = cv.encode_points([p, p, p])
    ks = cv.encode_scalars([0, 1, hm.R - 1])
    got = cv.decode_points(cv.scalar_mul(P, ks))
    assert got == [None, p, hm.g1_neg(p)]


def test_tree_sum(rng):
    pts = _host_pts(rng, 5)
    arr = cv.encode_points(pts)  # (5, 3, L)
    got = cv.decode_point(cv.tree_sum(arr, axis=0))
    assert got == hm.g1_sum(pts)


def test_fixed_base_msm(rng):
    bases = _host_pts(rng, 3)
    table = cv.FixedBaseTable(bases)
    B = 4
    scal = [[rng.randrange(hm.R) for _ in range(3)] for _ in range(B)]
    S = jnp.stack([cv.encode_scalars(row) for row in scal])  # (B, 3, L)
    got = cv.decode_points(table.msm(S))
    want = [hm.g1_multiexp(bases, row) for row in scal]
    assert got == want
