"""Compile-budget regression guards (VERDICT round-5 weak #4).

The staged pairing tiles and row-tiled kernels exist so that the number
of distinct XLA programs stays CONSTANT as batch size varies — a per-K /
per-batch-shape program explosion is what turned round 5 into rc=124 on
a 1-core-compile host. The `jax.core.compile.backend_compile_duration`
histogram (registered in `ops/__init__.py`) counts actual backend
compiles, so these tests pin the budget directly.
"""

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import batch, hostmath as hm
from fabric_token_sdk_tpu.ops import curve as cv, pairing as pr
from fabric_token_sdk_tpu.utils import metrics as mx

COMPILES = "jax.core.compile.backend_compile_duration.seconds"


def _compiles() -> int:
    return mx.REGISTRY.histogram(COMPILES).count


def _wf_args(batch_size: int, rng):
    bases = [hm.g1_mul(hm.G1_GEN, 3 + i) for i in range(3)]
    table = cv.FixedBaseTable(bases)
    # n = n_in + n_out + 2 = 6: the 2-in/2-out trailing shape that
    # test_batch_verify.py already compiles — running after it in the
    # tier-1 suite, this test adds zero compile time
    n = 6
    resp = np.zeros((batch_size, n, 3, 32), dtype=np.int32)
    stmt = np.zeros((batch_size, n, 3, 32), dtype=np.int32)
    chal = np.zeros((batch_size, 32), dtype=np.int32)
    for b in range(batch_size):
        chal[b] = np.asarray(cv.encode_scalars([rng.randrange(hm.R)]))[0]
        for j in range(n):
            stmt[b, j] = cv.encode_point(hm.g1_mul(hm.G1_GEN, 5 + b + j))
            resp[b, j] = np.asarray(
                cv.encode_scalars([rng.randrange(hm.R) for _ in range(3)])
            )
    return table, resp, stmt, chal


def test_row_tiled_kernel_program_count_is_batch_invariant(rng):
    """`_run_tiled` slices every batch into ROW_TILE slabs, so changing
    the batch size must compile ZERO new programs."""
    table, resp, stmt, chal = _wf_args(3, rng)
    before = _compiles()
    batch._run_tiled(batch._wf_kernel, resp, stmt, chal, consts=(table.flat,))
    first = _compiles() - before
    # one trailing shape -> at most one program (0 if an earlier test in
    # this session already compiled it)
    assert first <= 1, f"_wf_kernel compiled {first} programs for one shape"

    table2, resp2, stmt2, chal2 = _wf_args(11, rng)
    before = _compiles()
    batch._run_tiled(batch._wf_kernel, resp2, stmt2, chal2, consts=(table2.flat,))
    assert _compiles() - before == 0, (
        "changing batch size recompiled the row-tiled kernel — the "
        "ROW_TILE slab contract is broken"
    )


@pytest.mark.slow
def test_staged_pairing_program_budget(rng):
    """The staged pairing pipeline must cost at most 3 distinct programs
    (miller tile, per-K row product, final-exp tile) for a given K, zero
    new programs when only the batch size changes, and at most 1 tiny
    program for a new K."""
    P = hm.g1_mul(hm.G1_GEN, 7)
    Q = hm.g2_mul(hm.G2_GEN, 9)
    negP = hm.g1_neg(P)

    def staged(B, K):
        Ps = np.stack(
            [pr.encode_g1([P, negP] * (K // 2)) for _ in range(B)]
        )
        Qs = np.stack([pr.encode_g2([Q] * K) for _ in range(B)])
        return pr.pairing_product_staged(Ps, Qs)

    before = _compiles()
    gt = staged(2, 2)
    first = _compiles() - before
    # e(P,Q) * e(-P,Q) == 1 — the instrumentation rides a real verify
    assert np.asarray(pr.gt_is_one(gt)).all()
    # 3 tile programs (miller, per-K product, final-exp) + 1 slack for
    # incidental host-glue lowering; the invariance asserts below are the
    # real explosion guards
    assert first <= 4, f"staged pairing compiled {first} programs (budget 4)"

    before = _compiles()
    staged(5, 2)
    assert _compiles() - before == 0, (
        "batch-size change recompiled a staged pairing program"
    )

    before = _compiles()
    staged(2, 4)
    assert _compiles() - before <= 1, (
        "a new K must cost at most the tiny per-K row-product program"
    )

    # the staged-path counters recorded the work
    assert mx.REGISTRY.counter("pairing.staged.calls").value >= 3
    assert mx.REGISTRY.counter("pairing.staged.rows").value >= 9
