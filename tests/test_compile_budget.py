"""Compile-budget regression guards (VERDICT round-5 weak #4).

The staged stage/pairing tiles exist so that the number of distinct XLA
programs stays a SMALL CONSTANT as batch size, transfer shape
`(n_in, n_out)`, and parameter set vary — a per-shape program explosion
is what turned round 5 into rc=124 on a 1-core-compile host, and what
made the old fused `_wf_kernel` cost more than the whole tier-1 budget.
The `jax.core.compile.backend_compile_duration` histogram (registered in
`ops/__init__.py`) counts actual backend compiles, so these tests pin
the budget directly.
"""

import os
import random

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import batch, hostmath as hm
from fabric_token_sdk_tpu.crypto import token as tok, wellformedness as wf
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.ops import curve as cv, pairing as pr, stages as st
from fabric_token_sdk_tpu.utils import metrics as mx

COMPILES = "jax.core.compile.backend_compile_duration.seconds"

# every program the full staged BatchedTransferVerifier path may touch:
# 3x g1 msm + g1 mul/sub/to-affine + 3x g2 + miller + per-K product +
# final-exp + slack for incidental host-glue lowering
TRANSFER_PROGRAM_BUDGET = 16


def _compiles() -> int:
    return mx.REGISTRY.histogram(COMPILES).count


@pytest.fixture(scope="module")
def pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def _wf_txs(pp, rng, in_vals, out_vals, count):
    txs = []
    for _ in range(count):
        in_toks, in_w = tok.tokens_with_witness(in_vals, "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness(out_vals, "USD", pp.ped_params, rng)
        raw = wf.TransferWFProver(
            wf.TransferWFWitness(
                "USD",
                [w.value for w in in_w], [w.bf for w in in_w],
                [w.value for w in out_w], [w.bf for w in out_w],
            ),
            pp.ped_params, in_toks, out_toks, rng,
        ).prove()
        txs.append((in_toks, out_toks, raw))
    return txs


def test_stage_rows_program_count_is_batch_invariant(rng):
    """`stages.run_rows` slices every flat-row batch into ROW_TILE slabs,
    so changing the batch size must compile ZERO new programs — and the
    window table is an ARGUMENT, so a different table of the same base
    count must share the executable too."""
    bases = [hm.g1_mul(hm.G1_GEN, 3 + i) for i in range(3)]
    table = cv.FixedBaseTable(bases)

    def scal(B):
        return np.stack(
            [cv.encode_scalars([rng.randrange(hm.R) for _ in range(3)])
             for _ in range(B)]
        )

    before = _compiles()
    st.g1_msm_rows(table.flat, scal(3))
    first = _compiles() - before
    # one canonical tile shape -> at most one program (0 if an earlier
    # test in this session already compiled it)
    assert first <= 1, f"msm tile compiled {first} programs for one shape"

    before = _compiles()
    st.g1_msm_rows(table.flat, scal(11))
    assert _compiles() - before == 0, (
        "changing batch size recompiled the msm tile — the ROW_TILE slab "
        "contract is broken"
    )

    table2 = cv.FixedBaseTable([hm.g1_mul(hm.G1_GEN, 7 + i) for i in range(3)])
    before = _compiles()
    st.g1_msm_rows(table2.flat, scal(2))
    assert _compiles() - before == 0, (
        "a different parameter set recompiled the msm tile — tables must "
        "be arguments, not baked constants"
    )


def test_dispatch_ledger_pins_program_set_across_batch_sweep(rng):
    """The dispatch ledger is the witness for the compile-budget story:
    sweeping batch size across the msm row runner must grow DISPATCHES
    but never the set of distinct (plane, program) frames — one
    canonical tile program per plane, whatever the batch size. This is
    the same invariant `_compiles()` pins from the XLA side, asserted
    from the ledger side."""
    from fabric_token_sdk_tpu.utils import devobs

    bases = [hm.g1_mul(hm.G1_GEN, 31 + i) for i in range(3)]
    table = cv.FixedBaseTable(bases)

    def scal(B):
        return np.stack(
            [cv.encode_scalars([rng.randrange(hm.R) for _ in range(3)])
             for _ in range(B)]
        )

    before = devobs.snapshot()
    sweep = (1, 3, 11, 32)
    for B in sweep:
        st.g1_msm_rows(table.flat, scal(B))
    after = devobs.snapshot()

    def disp(snap, frame):
        return snap.get(frame, {}).get("dispatches", 0)

    grown = {f for f in after if disp(after, f) > disp(before, f)}
    # the whole sweep lands on ONE frame: the stages plane, the one
    # canonical 3-base msm tile program
    assert grown == {("stages", "g1_msm3_tile")}, grown
    frame = ("stages", "g1_msm3_tile")
    assert disp(after, frame) - disp(before, frame) == len(sweep)
    rows = after[frame]["rows"] - before.get(frame, {}).get("rows", 0)
    padded = after[frame]["padded_rows"] - before.get(frame, {}).get(
        "padded_rows", 0
    )
    assert rows == sum(sweep)
    assert padded == sum((-B) % st.ROW_TILE for B in sweep)
    # and the sweep compiled at most the one tile program (0 when an
    # earlier test already compiled it), never one per batch size
    assert after[frame]["compiles"] - before.get(frame, {}).get(
        "compiles", 0
    ) <= 1


def test_wf_verifier_is_transfer_shape_invariant(rng, pp):
    """The staged BatchedWFVerifier must compile ZERO new programs for a
    second, differently-shaped (n_in, n_out) block — the guarantee the
    old fused per-shape `_wf_kernel` lacked."""
    v = batch.BatchedWFVerifier(pp)
    got = v.verify(_wf_txs(pp, rng, [5, 10], [7, 8], 2))
    assert got.tolist() == [True, True]

    before = _compiles()
    got = v.verify(_wf_txs(pp, rng, [9], [4, 3, 2], 2))
    assert got.tolist() == [True, True]
    assert _compiles() - before == 0, (
        "a new (n_in, n_out) shape compiled new XLA programs — the staged "
        "WF path must be shape-invariant"
    )


def test_host_batch_path_compiles_zero_programs(rng, pp):
    """The batch-first HOST validation plane (FTS_HOST_BATCH) is pure
    host work — native ctypes multiexp, one batched sha256 dispatch,
    column arithmetic, thread-pool fan-out. Committing a zk block whose
    rows ALL route to the host passes (min_batch above the block size:
    every plannable row is a device leftover consumed by
    `_host_proof_batch`, signatures by the block sign batch) must
    compile ZERO XLA programs. No warmup gate: this holds cold."""
    from test_orderer import build_env, issue_to, manual_transfer
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
    from fabric_token_sdk_tpu.services.network import BlockPolicy

    network, parties, issuer, alice, bob = build_env(
        lambda: ZKATDLogDriver(pp),
        BlockPolicy(max_block_txs=8, min_batch=99, use_batched=True),
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5] * 3, "hb-seed")
    reqs = [
        manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"hb-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]

    hb_before = mx.REGISTRY.counter("hostbatch.proof.rows").value
    before = _compiles()
    events = network.submit_many([r.to_bytes() for r in reqs])
    assert all(e.status.value == "Valid" for e in events)
    # the block really rode the host batch pass...
    assert mx.REGISTRY.counter("hostbatch.proof.rows").value - hb_before == 3
    # ...which compiled nothing: the host path never touches XLA
    assert _compiles() - before == 0, (
        "the batch-first host validation path compiled XLA programs — "
        "host batching must stay off the device plane entirely"
    )


@pytest.mark.skipif(
    os.environ.get("FTS_WARMUP") != "1",
    reason="needs the FTS_WARMUP=1 session precompile (conftest fixture)",
)
def test_block_validation_compiles_zero_programs_after_warmup(rng, pp):
    """Non-slow guard for the ORDERER's batched plane: after the session
    warmup precompiled the canonical program set, committing a block of
    same-shape zkatdlog transfers through `Network.submit_many` (grouping
    -> BatchedTransferVerifier -> MVCC commit) must MISS the compilation
    cache zero times — the product path never pays a surprise compile."""
    from test_orderer import build_env, issue_to, manual_transfer
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
    from fabric_token_sdk_tpu.services.network import BlockPolicy

    network, parties, issuer, alice, bob = build_env(
        lambda: ZKATDLogDriver(pp), BlockPolicy(max_block_txs=8, min_batch=2)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5] * 4, "cb-seed")
    reqs = [
        manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"cb-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]

    bt_before = mx.REGISTRY.counter("batch.transfer.txs").value
    misses_before = mx.REGISTRY.counter(
        "jax.compilation_cache.cache_misses"
    ).value
    events = network.submit_many([r.to_bytes() for r in reqs])
    assert all(e.status.value == "Valid" for e in events)
    # the block really rode the device plane...
    assert mx.REGISTRY.counter("batch.transfer.txs").value - bt_before == 4
    # ...and it compiled nothing new
    misses = (
        mx.REGISTRY.counter("jax.compilation_cache.cache_misses").value
        - misses_before
    )
    assert misses == 0, (
        f"block validation missed the compilation cache {misses} time(s) "
        "after warmup() — the orderer's batched plane escaped the "
        "canonical program set"
    )


@pytest.mark.skipif(
    os.environ.get("FTS_WARMUP") != "1",
    reason="needs the FTS_WARMUP=1 session precompile (conftest fixture)",
)
def test_pipelined_blocks_compile_zero_programs_after_warmup(rng, pp):
    """Tentpole guard: the PIPELINED block engine is pure host-side
    scheduling — streaming TWO zk blocks through the verify/commit
    overlap (stage A on the driving thread, stage B on the commit
    worker) compiles zero new XLA programs and misses the compilation
    cache zero times post-warmup."""
    from test_orderer import build_env, issue_to, manual_transfer
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
    from fabric_token_sdk_tpu.services.network import BlockPolicy

    network, parties, issuer, alice, bob = build_env(
        lambda: ZKATDLogDriver(pp),
        BlockPolicy(max_block_txs=2, min_batch=2, pipeline=True),
    )
    assert network._engine is not None
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5] * 4, "pcb-seed")
    reqs = [
        manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"pcb-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]

    blocks_before = mx.REGISTRY.counter("orderer.pipeline.blocks").value
    compiles_before = _compiles()
    misses_before = mx.REGISTRY.counter(
        "jax.compilation_cache.cache_misses"
    ).value
    events = network.submit_many([r.to_bytes() for r in reqs])
    assert all(e.status.value == "Valid" for e in events)
    # two transfer blocks really streamed through the engine...
    assert (
        mx.REGISTRY.counter("orderer.pipeline.blocks").value - blocks_before
        >= 2
    )
    # ...with zero new program shapes and zero cache misses
    assert _compiles() - compiles_before == 0, (
        "the pipelined engine compiled a new XLA program — overlap must "
        "be host-side scheduling over the canonical tile executables"
    )
    misses = (
        mx.REGISTRY.counter("jax.compilation_cache.cache_misses").value
        - misses_before
    )
    assert misses == 0, (
        f"pipelined block validation missed the compilation cache "
        f"{misses} time(s) after warmup()"
    )


@pytest.mark.skipif(
    os.environ.get("FTS_WARMUP") != "1",
    reason="needs the FTS_WARMUP=1 session precompile (conftest fixture)",
)
def test_sharded_planes_compile_zero_programs_after_warmup(rng, pp):
    """Tentpole guard: the mesh-sharded dispatch (verify AND prove)
    reuses the compile-once tile executables — a dp x mp sharded block
    commit plus a sharded batched prove compile ZERO new programs and
    miss the compilation cache ZERO times post-warmup. Sharding is
    host-side dispatch, never a new XLA program."""
    from test_orderer import build_env, issue_to, manual_transfer
    from fabric_token_sdk_tpu.crypto.batch_prove import BatchedTransferProver
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
    from fabric_token_sdk_tpu.parallel import MeshConfig
    from fabric_token_sdk_tpu.services.network import BlockPolicy, Network

    mesh = MeshConfig.build(8, 2)
    network, parties, issuer, alice, bob = build_env(
        lambda: ZKATDLogDriver(pp), BlockPolicy(max_block_txs=8, min_batch=2)
    )
    # rebind the already-built network onto a sharded pipeline: the env
    # helper has no mesh hook, the pipeline does
    network._pipeline.mesh = mesh
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5] * 4, "shcb-seed")
    reqs = [
        manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"shcb-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]

    sharded_before = mx.REGISTRY.counter("stages.sharded_calls").value
    compiles_before = _compiles()
    misses_before = mx.REGISTRY.counter(
        "jax.compilation_cache.cache_misses"
    ).value
    events = network.submit_many([r.to_bytes() for r in reqs])
    assert all(e.status.value == "Valid" for e in events)
    # sharded prove of a fresh (1,1) group through the same guarantee
    in_toks, in_w = tok.tokens_with_witness([5], "USD", pp.ped_params, rng)
    out_toks, out_w = tok.tokens_with_witness([5], "USD", pp.ped_params, rng)
    proofs = BatchedTransferProver(pp, mesh=mesh).prove(
        [(in_w, out_w, in_toks, out_toks)], rng
    )
    assert len(proofs) == 1
    assert mx.REGISTRY.counter("stages.sharded_calls").value > sharded_before
    assert _compiles() - compiles_before == 0, (
        "the sharded dispatch compiled a new XLA program — it must reuse "
        "the canonical tile executables"
    )
    misses = (
        mx.REGISTRY.counter("jax.compilation_cache.cache_misses").value
        - misses_before
    )
    assert misses == 0, (
        f"sharded planes missed the compilation cache {misses} time(s) "
        "after warmup()"
    )


def test_foreign_cache_dir_is_never_loaded(tmp_path):
    """A persistent cache populated on a DIFFERENT host (mismatched
    HOST_FINGERPRINT marker) must be diverted away from — its AOT entries
    carry foreign CPU features ("could lead to SIGILL", the BENCH_r05
    rc=124) — with the skipped entries counted under
    `jax.cache.foreign_skipped`. A matching or unclaimed dir is reused."""
    from fabric_token_sdk_tpu import ops

    fp = ops.host_fingerprint()
    assert fp == ops.host_fingerprint(), "fingerprint must be stable"
    base = str(tmp_path / "cache")

    # unclaimed: this host claims it and uses it directly
    assert ops._resolve_cache_dir(base, fp) == base
    marker = tmp_path / "cache" / "HOST_FINGERPRINT"
    assert marker.read_text().strip() == fp
    # claimed by this host: reused
    assert ops._resolve_cache_dir(base, fp) == base

    # claimed by a foreign host holding two AOT entries: diverted, and
    # exactly the `-cache` payload files counted (not `-atime` companions)
    marker.write_text("feedfacefeedface\n")
    (tmp_path / "cache" / "jit_foo-cache").write_bytes(b"aot")
    (tmp_path / "cache" / "jit_foo-atime").write_bytes(b"t")
    (tmp_path / "cache" / "jit_bar-cache").write_bytes(b"aot")
    before = mx.REGISTRY.counter("jax.cache.foreign_skipped").value
    got = ops._resolve_cache_dir(base, fp)
    assert got == str(tmp_path / "cache" / f"host-{fp}")
    assert (
        mx.REGISTRY.counter("jax.cache.foreign_skipped").value - before == 2
    )
    # the diverted dir resolves consistently on the next process
    assert ops._resolve_cache_dir(base, fp) == got

    # a torn claim (empty marker: claimant died mid-write) is repaired,
    # not treated as a permanent wildcard match
    marker.write_text("")
    assert ops._resolve_cache_dir(base, fp) == base
    assert marker.read_text().strip() == fp


def _prove_reqs(pp, rng, in_vals, out_vals, count):
    reqs = []
    for _ in range(count):
        in_toks, in_w = tok.tokens_with_witness(in_vals, "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness(out_vals, "USD", pp.ped_params, rng)
        reqs.append((in_w, out_w, in_toks, out_toks))
    return reqs


@pytest.mark.skipif(
    os.environ.get("FTS_WARMUP") != "1",
    reason="needs the FTS_WARMUP=1 session precompile (conftest fixture)",
)
def test_batch_prove_compiles_zero_programs_after_warmup(rng, pp):
    """Non-slow guard for the PROVE plane: after the session warmup,
    batch-proving — including a NEW `(n_in, n_out)` shape — must miss
    the compilation cache zero times and compile zero new programs: the
    batched prover is a composition of the same canonical tiles the
    warmup set covers (`warmup.PROVER_PROGRAMS`)."""
    from fabric_token_sdk_tpu.crypto import batch_prove, transfer as tr

    prover = batch_prove.BatchedTransferProver(pp)
    misses_before = mx.REGISTRY.counter(
        "jax.compilation_cache.cache_misses"
    ).value
    reqs = _prove_reqs(pp, rng, [5, 10], [7, 8], 2)
    proofs = prover.prove(reqs, rng)

    before = _compiles()
    reqs2 = _prove_reqs(pp, rng, [9], [4, 3, 2], 1)
    proofs2 = prover.prove(reqs2, rng)
    assert _compiles() - before == 0, (
        "a new transfer shape compiled new XLA programs — the batched "
        "prover escaped the canonical stage-tile set"
    )
    misses = (
        mx.REGISTRY.counter("jax.compilation_cache.cache_misses").value
        - misses_before
    )
    assert misses == 0, (
        f"batch proving missed the compilation cache {misses} time(s) "
        "after warmup() — warmup.PROVER_PROGRAMS is incomplete"
    )
    # the device-proved proofs are real: the host verifier accepts them
    for (_, _, inputs, outputs), proof in zip(reqs + reqs2, proofs + proofs2):
        tr.TransferVerifier(inputs, outputs, pp).verify(proof)


@pytest.mark.slow
def test_batched_prover_program_budget_and_shape_invariance(rng, pp):
    """Full device prove path (WF + range + membership pairing): at most
    TRANSFER_PROGRAM_BUDGET distinct programs ever — the prover adds only
    the tiny Jacobian-add tile beyond the verify set — and a second,
    differently-shaped batch compiles ZERO new programs."""
    from fabric_token_sdk_tpu.crypto import batch_prove, transfer as tr

    prover = batch_prove.BatchedTransferProver(pp)
    before = _compiles()
    reqs = _prove_reqs(pp, rng, [5, 10], [7, 8], 2)
    proofs = prover.prove(reqs, rng)
    first = _compiles() - before
    assert first <= TRANSFER_PROGRAM_BUDGET, (
        f"staged prove path compiled {first} programs "
        f"(budget {TRANSFER_PROGRAM_BUDGET})"
    )

    before = _compiles()
    reqs2 = _prove_reqs(pp, rng, [9], [5, 4], 1)
    proofs2 = prover.prove(reqs2, rng)
    assert _compiles() - before == 0, (
        "a new transfer shape compiled new XLA programs — the staged "
        "prove path must be shape-invariant"
    )

    for (_, _, inputs, outputs), proof in zip(reqs + reqs2, proofs + proofs2):
        tr.TransferVerifier(inputs, outputs, pp).verify(proof)


@pytest.mark.slow
def test_transfer_verifier_program_budget_and_shape_invariance(rng, pp):
    """Full staged BatchedTransferVerifier (WF + membership pairing +
    range equality): at most TRANSFER_PROGRAM_BUDGET distinct programs
    ever, and a second differently-shaped block compiles ZERO new ones."""
    from fabric_token_sdk_tpu.crypto import transfer as tr

    def transfer_txs(in_vals, out_vals, count):
        txs = []
        for _ in range(count):
            in_toks, in_w = tok.tokens_with_witness(
                in_vals, "USD", pp.ped_params, rng
            )
            out_toks, out_w = tok.tokens_with_witness(
                out_vals, "USD", pp.ped_params, rng
            )
            proof = tr.TransferProver(
                in_w, out_w, in_toks, out_toks, pp, rng
            ).prove()
            txs.append((in_toks, out_toks, proof))
        return txs

    v = batch.BatchedTransferVerifier(pp)
    before = _compiles()
    got = v.verify(transfer_txs([5, 10], [7, 8], 2))
    assert got.tolist() == [True, True]
    first = _compiles() - before
    assert first <= TRANSFER_PROGRAM_BUDGET, (
        f"staged transfer path compiled {first} programs "
        f"(budget {TRANSFER_PROGRAM_BUDGET})"
    )

    # different (n_in, n_out) AND different batch size: zero new programs
    before = _compiles()
    got = v.verify(transfer_txs([9], [5, 4], 1))
    assert got.tolist() == [True]
    assert _compiles() - before == 0, (
        "a new transfer shape compiled new XLA programs — the staged "
        "path must be shape-invariant"
    )

    # empty batch short-circuits without device work
    before = _compiles()
    assert v.verify([]).tolist() == []
    assert _compiles() - before == 0


@pytest.mark.slow
def test_warmup_precompiles_whole_stage_set(rng):
    """After `warmup()`, exercising every group-math stage on real data
    must compile NOTHING new: every program replays from the compilation
    cache. NOTE: this jax's `backend_compile_duration` event also fires
    on persistent-cache LOADS (retrieval time), so the no-new-compiles
    signal is `cache_misses == 0` — exactly what `ftsmetrics show`'s
    compile-summary line surfaces."""
    from fabric_token_sdk_tpu.ops import warmup as wu

    summary = wu.warmup(include_pairing=False)
    assert summary["programs"] == len(list(st.stage_programs()))

    from fabric_token_sdk_tpu.ops import curve2 as cv2

    pts = [hm.g1_mul(hm.G1_GEN, 3 + i) for i in range(3)]
    jac = np.stack([cv.encode_point(p) for p in pts])
    ks = np.stack([cv.encode_scalars([rng.randrange(hm.R)])[0] for _ in pts])
    table1 = cv.FixedBaseTable(pts[:1])
    table2 = cv.FixedBaseTable(pts[:2])
    table3 = cv.FixedBaseTable(pts)
    g2pts = np.asarray(
        cv2.encode_points([hm.g2_mul(hm.G2_GEN, 5 + i) for i in range(3)])
    )

    misses_before = mx.REGISTRY.counter(
        "jax.compilation_cache.cache_misses"
    ).value
    st.g1_msm_rows(table1.flat, ks[:, None, :])
    st.g1_msm_rows(table2.flat, np.stack([ks, ks], axis=1))
    st.g1_msm_rows(table3.flat, np.stack([ks, ks, ks], axis=1))
    st.g1_mul_rows(jac, ks)
    st.g1_add_rows(jac, jac)
    st.g1_sub_rows(jac, jac)
    st.g1_to_affine_rows(jac)
    st.g2_mul_rows(g2pts, ks)
    st.g2_add_rows(g2pts, g2pts)
    st.g2_to_affine_rows(g2pts)
    misses = (
        mx.REGISTRY.counter("jax.compilation_cache.cache_misses").value
        - misses_before
    )
    assert misses == 0, (
        f"{misses} stage program(s) missed the compilation cache after "
        "warmup() — the AOT precompile set is incomplete"
    )


@pytest.mark.slow
def test_staged_pairing_program_budget(rng):
    """The staged pairing pipeline must cost at most 3 distinct programs
    (miller tile, per-K row product, final-exp tile) for a given K, zero
    new programs when only the batch size changes, and at most 1 tiny
    program for a new K."""
    P = hm.g1_mul(hm.G1_GEN, 7)
    Q = hm.g2_mul(hm.G2_GEN, 9)
    negP = hm.g1_neg(P)

    def staged(B, K):
        Ps = np.stack(
            [pr.encode_g1([P, negP] * (K // 2)) for _ in range(B)]
        )
        Qs = np.stack([pr.encode_g2([Q] * K) for _ in range(B)])
        return pr.pairing_product_staged(Ps, Qs)

    before = _compiles()
    gt = staged(2, 2)
    first = _compiles() - before
    # e(P,Q) * e(-P,Q) == 1 — the instrumentation rides a real verify
    assert pr.gt_is_one_host(gt).all()
    # 3 tile programs (miller, per-K product, final-exp) + 1 slack for
    # incidental host-glue lowering; the invariance asserts below are the
    # real explosion guards
    assert first <= 4, f"staged pairing compiled {first} programs (budget 4)"

    before = _compiles()
    staged(5, 2)
    assert _compiles() - before == 0, (
        "batch-size change recompiled a staged pairing program"
    )

    before = _compiles()
    staged(2, 4)
    assert _compiles() - before <= 1, (
        "a new K must cost at most the tiny per-K row-product program"
    )

    # the staged-path counters recorded the work
    assert mx.REGISTRY.counter("pairing.staged.calls").value >= 3
    assert mx.REGISTRY.counter("pairing.staged.rows").value >= 9
