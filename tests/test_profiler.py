"""Host-path profiler: zero-cost-when-off, exclusive sub-leg timing,
bounded sampling, and the differential no-perturbation contract.

The profiler is an observer: off (the default) it must add NO threads
and leave the leg timers as passthroughs; on, it may only aggregate —
accept/reject verdicts of an identical workload must not change.
"""
import random
import threading
import time

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network, TxStatus
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import metrics as mx
from fabric_token_sdk_tpu.utils import profiler


@pytest.fixture(autouse=True)
def _sampler_off():
    yield
    profiler.stop()


# ===================================================================
# zero cost when off
# ===================================================================


def test_off_means_zero_profiler_threads(monkeypatch):
    monkeypatch.delenv("FTS_PROF_HZ", raising=False)
    assert profiler.start() is None
    assert profiler.active() is None
    monkeypatch.setenv("FTS_PROF_HZ", "0")
    assert profiler.start() is None
    names = [t.name for t in threading.enumerate()]
    assert not any(n.startswith("fts-prof") for n in names)


def test_leg_is_passthrough_without_collector():
    before_totals = profiler.leg_totals()
    before_count = mx.REGISTRY.histogram("ledger.host.unmarshal.seconds").count
    with profiler.leg("unmarshal"):
        pass
    assert profiler.leg_totals() == before_totals
    assert (
        mx.REGISTRY.histogram("ledger.host.unmarshal.seconds").count
        == before_count
    )


def test_start_stop_lifecycle():
    p = profiler.start(hz=200.0)
    assert p is not None and p.running()
    assert any(t.name == "fts-prof" for t in threading.enumerate())
    # idempotent: a second start returns the live sampler
    assert profiler.start(hz=200.0) is p
    stopped = profiler.stop()
    assert stopped is p and not p.running()
    assert profiler.active() is None
    assert profiler.stop() is None


# ===================================================================
# exclusive sub-leg timing
# ===================================================================


def test_nested_legs_bill_exclusively():
    with profiler.collect() as legs:
        with profiler.leg("conservation"):
            time.sleep(0.02)
            with profiler.leg("sig_verify"):
                time.sleep(0.03)
            time.sleep(0.01)
    # the inner leg's wall time is excluded from the outer leg's self
    # time — the legs sum toward, never beyond, the window's wall clock
    assert legs["sig_verify"] >= 0.03
    assert 0.02 <= legs["conservation"] < 0.06
    assert legs["conservation"] + legs["sig_verify"] < 0.09


def test_collect_windows_restore_and_totals_accumulate():
    t0 = profiler.leg_totals().get("input_match", 0.0)
    with profiler.collect() as outer:
        with profiler.leg("input_match"):
            pass
        with profiler.collect() as inner:
            with profiler.leg("input_match"):
                pass
        assert "input_match" in inner
    assert "input_match" in outer
    # cumulative totals saw both windows
    assert profiler.leg_totals()["input_match"] >= t0
    # outside any window: passthrough again
    before = profiler.leg_totals()
    with profiler.leg("input_match"):
        pass
    assert profiler.leg_totals() == before


# ===================================================================
# bounded sampling + roles
# ===================================================================


def _parked_thread(name, release, role=None, depth=0):
    """Park a thread `depth` recursion frames deep — distinct depths
    yield distinct collapsed stacks (same frames, different counts)."""
    ready = threading.Event()

    def park(d):
        if d > 0:
            return park(d - 1)
        if role:
            profiler.set_thread_role(role)
        ready.set()
        release.wait(timeout=30)

    t = threading.Thread(target=park, args=(depth,), name=name, daemon=True)
    t.start()
    ready.wait(timeout=10)
    return t


def test_sampler_table_is_bounded_and_drops_are_counted():
    release = threading.Event()
    threads = [
        _parked_thread(f"park-{i}", release, depth=i) for i in range(3)
    ]
    try:
        p = profiler.SamplingProfiler(hz=0, max_stacks=1)
        p.sample()
        assert p.stack_count() == 1
        assert p.dropped >= 1
        assert p.samples == 1
        # known stacks keep counting even at the cap
        p.sample()
        assert p.stack_count() == 1
        assert sum(p.collapsed().values()) >= 2
    finally:
        release.set()
        for t in threads:
            t.join(timeout=10)


def test_roles_registration_and_name_classification():
    release = threading.Event()
    threads = [
        _parked_thread("worker-x", release, role="client"),
        _parked_thread("fts-block-commit", release),
    ]
    try:
        p = profiler.SamplingProfiler(hz=0, max_stacks=100)
        p.sample()
        assert p.collapsed(role="client"), p.collapsed()
        assert p.collapsed(role="commit-worker"), p.collapsed()
        # collapsed keys are flamegraph-shaped: role;mod:func;...
        for key in p.collapsed(role="client"):
            assert key.startswith("client;")
            assert ":" in key.split(";", 1)[1]
    finally:
        release.set()
        for t in threads:
            t.join(timeout=10)


# ===================================================================
# differential: profiling never perturbs verdicts
# ===================================================================


def _run_scenario():
    """A deterministic mixed-verdict workload: issue, two transfers of
    which the second double-spends. Returns ([statuses], breakdown)."""
    pp = FabTokenPublicParams()
    network = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(max_block_txs=8),
    )
    parties = {
        name: Party(name, FabTokenDriver(pp), network)
        for name in ("issuer-node", "alice-node", "bob-node")
    }
    parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet("alice", anonymous=False)
    bob = parties["bob-node"].new_owner_wallet("bob", anonymous=False)
    tx = Transaction(parties["issuer-node"], "seed")
    tx.issue("issuer", "USD", [5], [alice.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(None)
    tx.submit()
    alice_p = parties["alice-node"]
    tid = alice_p.vault.token_ids()[0]

    def spend(anchor):
        req = alice_p.tms.new_request(anchor)
        tokens, metas = alice_p.vault.get_many([tid])
        alice_p.tms.add_transfer(
            req, [tid], tokens, metas, "USD", [5],
            [bob.recipient_identity()],
        )
        alice_p.tms.sign_transfers(req)
        return req.to_bytes()

    events = network.submit_many([spend("pay-ok"), spend("pay-dup")])
    bd = network.health()["last_block"]["breakdown"]
    return [e.status for e in events], bd


def test_sampler_never_perturbs_verdicts():
    base_statuses, base_bd = _run_scenario()
    assert base_statuses == [TxStatus.VALID, TxStatus.INVALID]
    p = profiler.start(hz=500.0)
    assert p is not None
    try:
        prof_statuses, prof_bd = _run_scenario()
    finally:
        profiler.stop()
    assert prof_statuses == base_statuses
    # both runs decomposed the host leg the same way (keys, not timings)
    for leg_name in profiler.LEGS:
        assert f"host_{leg_name}_s" in base_bd
        assert f"host_{leg_name}_s" in prof_bd


def test_breakdown_sublegs_cover_host_leg(monkeypatch):
    # the coverage contract describes the SCALAR host path: with the
    # block-level batch passes on, sign/conservation work moves into
    # separately-timed batch legs and the per-tx sub-legs legitimately
    # shrink below the 50% floor. Pin the scalar path explicitly.
    monkeypatch.setenv("FTS_HOST_BATCH", "0")
    _statuses, bd = _run_scenario()
    host = bd["host_validate_s"]
    sublegs = sum(bd[f"host_{leg}_s"] for leg in profiler.LEGS)
    assert host > 0
    # exclusive sub-legs never sum past the leg they decompose (small
    # epsilon: the breakdown rounds each leg to 1us independently)
    assert sublegs <= host + 1e-3
    # and they explain most of it — the attribution the PR exists for
    assert sublegs / host > 0.5, bd
