"""Tests: HTLC interop, NFT service, tokengen CLI, quantity model."""
import hashlib
import time

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.api.wallet import AuditorWallet
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.drivers import identity
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.models.quantity import Quantity
from fabric_token_sdk_tpu.services.interop import htlc
from fabric_token_sdk_tpu.services.network import Network
from fabric_token_sdk_tpu.services.nfttx import NFTService
from fabric_token_sdk_tpu.services.ttx import Party, Transaction


def test_quantity_model():
    q = Quantity.from_uint64(255)
    assert q.hex() == "0xff" and q.decimal() == "255"
    assert Quantity.from_hex("0xff").value == 255
    assert q.add(Quantity.from_uint64(1)).value == 256
    with pytest.raises(ValueError):
        q.sub(Quantity.from_uint64(256))
    with pytest.raises(ValueError):
        Quantity(1 << 64, 64)
    with pytest.raises(ValueError):
        Quantity.from_hex("ff")


def test_htlc_claim_reclaim(rng):
    sender = sign.keygen(rng)
    recipient = sign.keygen(rng)
    preimage = b"super-secret"
    h = hashlib.sha256(preimage).digest()
    deadline = time.time() + 3600
    script = htlc.lock(
        identity.pk_identity(sender.public), identity.pk_identity(recipient.public),
        h, deadline,
    )
    ident = script.to_identity()
    msg = b"spend-tx"
    # claim with preimage before deadline
    sig = htlc.claim(script, preimage, lambda m: recipient.sign(m, rng), msg)
    identity.verify_signature(ident, msg, sig)
    # wrong preimage rejected at claim time
    with pytest.raises(ValueError):
        htlc.claim(script, b"wrong", lambda m: recipient.sign(m, rng), msg)
    # forged claim signature rejected at verification
    forged = htlc.HTLCClaimSignature(b"wrong", recipient.sign(msg, rng)).to_bytes()
    with pytest.raises(ValueError):
        identity.verify_signature(ident, msg, forged)
    # reclaim only after deadline
    with pytest.raises(ValueError):
        htlc.reclaim(script, lambda m: sender.sign(m, rng), msg)
    sig2 = htlc.reclaim(script, lambda m: sender.sign(m, rng), msg, now=deadline + 1)
    htlc.verify_htlc_spend(ident, msg, sig2, now=deadline + 1)
    # before the deadline a bare sender sig does not verify (claim rules)
    with pytest.raises(ValueError):
        identity.verify_signature(ident, msg, sig2)


def test_htlc_token_flow(rng):
    """Lock fabtokens under an HTLC script and claim them."""
    pp = FabTokenPublicParams()
    vdrv = FabTokenDriver(pp)
    aw = AuditorWallet("auditor", sign.keygen())
    net = Network(RequestValidator(vdrv, aw.identity))
    from fabric_token_sdk_tpu.services.auditor import AuditorService
    auditor = AuditorService(FabTokenDriver(pp), aw)
    issuer_p = Party("issuer", FabTokenDriver(pp), net, aw.identity)
    alice_p = Party("alice", FabTokenDriver(pp), net, aw.identity)
    bob_p = Party("bob", FabTokenDriver(pp), net, aw.identity)
    iw = issuer_p.new_issuer_wallet("issuer"); pp.add_issuer(iw.identity)
    alice = alice_p.new_owner_wallet("alice", False)
    bob = bob_p.new_owner_wallet("bob", False)

    tx = Transaction(issuer_p, "mint")
    tx.issue("issuer", "BTC", [5], [alice.recipient_identity()], anonymous=False)
    tx.collect_endorsements(auditor); tx.submit()

    preimage = b"swap-secret"
    script = htlc.lock(
        alice.recipient_identity(), bob.recipient_identity(),
        hashlib.sha256(preimage).digest(), time.time() + 3600,
    )
    tx2 = Transaction(alice_p, "lock")
    tx2.transfer("alice", "BTC", [5], [script.to_identity()])
    tx2.collect_endorsements(auditor); tx2.submit()
    assert alice_p.balance("BTC") == 0

    # bob claims: build transfer spending the script token with a claim sig
    from fabric_token_sdk_tpu.models.token import ID
    script_id = [i for i in [ID("lock", 0)] if net.exists(i)][0]
    out = net.resolve_input(script_id)
    tx3 = Transaction(bob_p, "claim")
    bob_p.tms.add_transfer(
        tx3.request, [script_id], [out], [out], "BTC", [5],
        [bob.recipient_identity()],
    )
    payload = tx3.request.marshal_to_sign()
    tx3.request.transfers[0].signatures = [
        htlc.claim(script, preimage, lambda m: bob.key.sign(m), payload)
    ]
    auditor.audit(tx3.request)
    tx3.submit()
    assert bob_p.balance("BTC") == 5


def test_nft_flow(rng):
    pp = FabTokenPublicParams()
    vdrv = FabTokenDriver(pp)
    aw = AuditorWallet("auditor", sign.keygen())
    net = Network(RequestValidator(vdrv, aw.identity))
    from fabric_token_sdk_tpu.services.auditor import AuditorService
    auditor = AuditorService(FabTokenDriver(pp), aw)
    issuer_p = Party("issuer", FabTokenDriver(pp), net, aw.identity)
    alice_p = Party("alice", FabTokenDriver(pp), net, aw.identity)
    bob_p = Party("bob", FabTokenDriver(pp), net, aw.identity)
    iw = issuer_p.new_issuer_wallet("issuer"); pp.add_issuer(iw.identity)
    alice = alice_p.new_owner_wallet("alice", False)
    bob = bob_p.new_owner_wallet("bob", False)

    state = {"artist": "banksy", "work": "ttx #1"}
    nft_issuer = NFTService(issuer_p)
    token_type = nft_issuer.issue("issuer", state, alice.recipient_identity(), auditor)
    alice_nft = NFTService(alice_p)
    assert alice_nft.my_nfts() == [token_type]
    assert alice_nft.state_matches(token_type, state)
    assert not alice_nft.state_matches(token_type, {"artist": "unknown", "work": "x"})
    alice_nft.transfer("alice", token_type, bob.recipient_identity(), auditor)
    assert alice_nft.my_nfts() == []
    assert NFTService(bob_p).my_nfts() == [token_type]


def test_tokengen_cli(tmp_path):
    import sys
    sys.path.insert(0, "cmd")
    import tokengen
    out = str(tmp_path / "arts")
    tokengen.main(["gen", "fabtoken", "--output", out, "--issuers", "2",
                   "--auditor", "--seed", "7"])
    from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenPublicParams as FPP
    raw = open(f"{out}/fabtoken_pp.json", "rb").read()
    pp = FPP.deserialize(raw)
    assert len(pp.issuers) == 2 and pp.auditor
    out2 = str(tmp_path / "arts2")
    tokengen.main(["gen", "dlog", "--output", out2, "--base", "2",
                   "--exponent", "1", "--seed", "7"])
    from fabric_token_sdk_tpu.crypto.setup import PublicParams
    pp2 = PublicParams.deserialize(open(f"{out2}/zkatdlog_pp.json", "rb").read())
    pp2.validate()
