"""Resilience layer: bounded device dispatch + per-plane circuit breakers.

Pins the PR-15 contract (utils/resilience.py):

* `CircuitBreaker` state machine — closed/open/half-open, failure-count
  and consecutive-timeout thresholds, monotonic cooldown, single-probe
  half-open admission, `FTS_BREAKER_*` env config, thread safety;
* `bounded_call` — inline when unbounded, result/exception passthrough,
  `DeviceTimeout` at the deadline, straggler discard (a worker that
  completes AFTER abandonment is counted, its result never applied);
* the `hang` fault kind (utils/faults.py) — blocks until disarm or cap,
  counts `faults.injected.*`, env-parseable;
* differential identity under a hung device plane on BOTH block engines:
  with `hang` injected at `batch.verify`, a zk block commits via host
  fallback within the deadline + slack, verdicts identical to the
  fault-free run (batching can accelerate but never change
  accept/reject — now including calls that never return);
* straggler discard at the block level: the abandoned verify worker
  completing after the block resolved must not double-apply verdicts or
  corrupt the block counters;
* the sign plane's construction-failure latch replacement: a transient
  failure opens the breaker, skips collection while open, and HEALS via
  the half-open probe (the old latch disabled the plane forever);
* `ftstop top` renders the breaker column from `ops.health`.
"""

import random
import threading
import time

import pytest

from fabric_token_sdk_tpu.api.request import (
    IssueRecord,
    TokenRequest,
    TransferRecord,
)
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers import identity
from fabric_token_sdk_tpu.drivers.fabtoken import (
    FabTokenDriver,
    FabTokenPublicParams,
)
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.network import BlockPolicy, Network, TxStatus
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import faults, resilience
from fabric_token_sdk_tpu.utils import metrics as mx


def _counter(name):
    return mx.REGISTRY.counter(name).value


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


# ===================================================================
# CircuitBreaker state machine
# ===================================================================


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _breaker(**kw):
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("timeout_threshold", 2)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("clock", _Clock())
    return resilience.CircuitBreaker("unit", **kw)


def test_breaker_opens_on_consecutive_failures():
    b = _breaker()
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"  # below threshold
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()
    assert b.rejecting()


def test_breaker_success_resets_failure_streak():
    b = _breaker()
    b.record_failure()
    b.record_failure()
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"  # streak restarted, never reached 3


def test_breaker_consecutive_timeouts_trip_faster():
    b = _breaker()
    b.record_failure(timeout=True)
    assert b.state == "closed"
    b.record_failure(timeout=True)
    assert b.state == "open"  # 2 consecutive timeouts < 3 failures
    # ... and a non-timeout failure resets the TIMEOUT streak only
    b2 = _breaker()
    b2.record_failure(timeout=True)
    b2.record_failure()  # failure #2, but timeout streak broken
    b2.record_failure(timeout=True)
    assert b2.state == "open"  # trips via failure threshold (3), not timeouts


def test_breaker_half_open_single_probe_then_close():
    clk = _Clock()
    b = _breaker(clock=clk)
    for _ in range(3):
        b.record_failure()
    assert not b.allow()  # open: rejected
    clk.t += 9.9
    assert not b.allow()  # cooldown not yet expired
    clk.t += 0.2
    assert b.state == "half-open"
    assert not b.rejecting()  # a probe is available: NOT hard-rejecting
    assert b.allow()  # the single probe
    assert not b.allow()  # second caller rejected while probe in flight
    b.record_success()
    assert b.state == "closed"
    assert b.allow()


def test_breaker_failed_probe_reopens_and_restarts_cooldown():
    clk = _Clock()
    b = _breaker(clock=clk)
    for _ in range(3):
        b.record_failure()
    clk.t += 10.1
    assert b.allow()  # probe
    b.record_failure()
    assert b.state == "open"
    clk.t += 5.0
    assert not b.allow()  # cooldown restarted at probe failure
    clk.t += 5.2
    assert b.allow()  # next probe due
    b.record_success()
    assert b.state == "closed"


def test_breaker_trip_now_opens_on_first_failure():
    """`trip_now` (structural failures like verifier construction OOM)
    opens regardless of thresholds — latch parity — and still heals via
    the half-open probe, unlike the latch."""
    clk = _Clock()
    b = _breaker(clock=clk)  # thresholds 3/2: one plain failure won't trip
    b.record_failure(trip_now=True)
    assert b.state == "open"
    clk.t += 10.1
    assert b.allow()  # the probe
    b.record_success()
    assert b.state == "closed"


def test_breaker_env_config(monkeypatch):
    monkeypatch.setenv("FTS_BREAKER_FAILURES", "7")
    monkeypatch.setenv("FTS_BREAKER_TIMEOUTS", "4")
    monkeypatch.setenv("FTS_BREAKER_COOLDOWN_S", "1.5")
    resilience.reset()
    b = resilience.breaker("envtest")
    assert b.failure_threshold == 7
    assert b.timeout_threshold == 4
    assert b.cooldown_s == 1.5


def test_breaker_transition_counters_and_state_gauge():
    resilience.reset()
    o0, c0, p0, r0 = (
        _counter("resilience.breaker.open"),
        _counter("resilience.breaker.close"),
        _counter("resilience.breaker.probe"),
        _counter("resilience.breaker.rejected"),
    )
    b = resilience.breaker("gaugetest")
    b.failure_threshold, b.timeout_threshold, b.cooldown_s = 1, 1, 0.05
    b.record_failure()
    assert _counter("resilience.breaker.open") - o0 == 1
    assert mx.REGISTRY.gauge("resilience.breaker.state.gaugetest").value == 2
    assert not b.allow()
    assert _counter("resilience.breaker.rejected") - r0 == 1
    time.sleep(0.06)
    assert b.allow()
    assert _counter("resilience.breaker.probe") - p0 == 1
    b.record_success()
    assert _counter("resilience.breaker.close") - c0 == 1
    assert mx.REGISTRY.gauge("resilience.breaker.state.gaugetest").value == 0
    assert resilience.breaker_states()["gaugetest"] == "closed"


def test_breaker_thread_safety():
    b = _breaker(failure_threshold=2, cooldown_s=0.001)

    def churn():
        for _ in range(200):
            if b.allow():
                b.record_failure()
            b.record_success()
            b.state

    threads = [threading.Thread(target=churn) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert b.state in ("closed", "open", "half-open")


# ===================================================================
# bounded_call
# ===================================================================


def test_bounded_call_unbounded_runs_inline():
    caller = threading.current_thread()
    seen = []
    out = resilience.bounded_call(
        lambda: seen.append(threading.current_thread()) or 7, 0, plane="t"
    )
    assert out == 7 and seen == [caller]
    # None is unbounded too
    assert resilience.bounded_call(lambda: 8, None, plane="t") == 8


def test_bounded_call_result_and_exception_passthrough():
    assert resilience.bounded_call(lambda: [1, 2], 5.0, plane="t") == [1, 2]
    with pytest.raises(ValueError, match="boom"):
        resilience.bounded_call(
            lambda: (_ for _ in ()).throw(ValueError("boom")), 5.0, plane="t"
        )


def test_bounded_call_timeout_and_straggler_discard():
    t0 = _counter("resilience.bounded.timeouts")
    s0 = _counter("resilience.bounded.stragglers")
    release = threading.Event()

    def slow():
        release.wait(10)
        return "late"

    start = time.monotonic()
    with pytest.raises(resilience.DeviceTimeout):
        resilience.bounded_call(slow, 0.1, plane="t")
    assert time.monotonic() - start < 5  # returned at the deadline, not 10s
    assert _counter("resilience.bounded.timeouts") - t0 == 1
    release.set()  # the abandoned worker now completes
    deadline = time.monotonic() + 10
    while (
        _counter("resilience.bounded.stragglers") == s0
        and time.monotonic() < deadline
    ):
        time.sleep(0.01)
    assert _counter("resilience.bounded.stragglers") - s0 == 1


def test_straggler_drain_joins_abandoned_workers():
    """Abandoned workers are tracked and `drain_stragglers` (the exit
    hook's body) joins the ones that finish within the budget — the
    guard against a daemon thread segfaulting interpreter teardown."""
    release = threading.Event()
    with pytest.raises(resilience.DeviceTimeout):
        resilience.bounded_call(lambda: release.wait(30), 0.05, plane="t")
    assert not resilience.drain_stragglers(0.05)  # still hung: not drained
    release.set()
    assert resilience.drain_stragglers(10.0)  # released: drained clean


def test_device_deadline_env_resolution(monkeypatch):
    monkeypatch.delenv("FTS_DEVICE_DEADLINE_S", raising=False)
    monkeypatch.delenv("FTS_DEVICE_DEADLINE_VERIFY_S", raising=False)
    # CPU backend: commit-path planes default UNBOUNDED (a cold compile
    # legitimately takes minutes on the emulated plane)
    assert resilience.device_deadline_s("verify") == 0.0
    assert resilience.device_deadline_s("prove") == 0.0
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_S", "3.5")
    assert resilience.device_deadline_s("verify") == 3.5
    assert resilience.device_deadline_s("sign") == 3.5
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_VERIFY_S", "1.25")
    assert resilience.device_deadline_s("verify") == 1.25  # per-plane wins
    assert resilience.device_deadline_s("sign") == 3.5
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_VERIFY_S", "0")
    assert resilience.device_deadline_s("verify") == 0.0  # 0 = unbounded


def test_cancel_probe_releases_the_half_open_slot():
    """A caller that consumed the half-open probe but found nothing to
    dispatch (driver without a batched plane) must release it, or the
    breaker would wedge in half-open forever — the exact
    process-lifetime latch this layer exists to remove."""
    clk = _Clock()
    b = _breaker(clock=clk)
    for _ in range(3):
        b.record_failure()
    clk.t += 10.1
    assert b.allow()  # probe consumed
    b.cancel_probe()  # ...but nothing was dispatched
    assert b.state == "half-open"
    assert b.allow()  # the slot is available again, not wedged
    b.record_success()
    assert b.state == "closed"


# ===================================================================
# The hang fault kind
# ===================================================================


def test_hang_fault_blocks_until_disarm():
    faults.arm("unit.hang", "hang", count=1, delay_s=30)
    fired = threading.Event()

    def firer():
        faults.fire("unit.hang")
        fired.set()

    f0 = _counter("faults.injected.unit.hang")
    t = threading.Thread(target=firer, daemon=True)
    t0 = time.monotonic()
    t.start()
    time.sleep(0.05)
    assert not fired.is_set()  # blocked, not sleeping-and-done
    faults.disarm("unit.hang")
    assert fired.wait(5)
    assert time.monotonic() - t0 < 5  # released by disarm, not the cap
    assert _counter("faults.injected.unit.hang") - f0 == 1


def test_hang_fault_cap_releases_without_disarm():
    faults.arm("unit.cap", "hang", count=1, delay_s=0.1)
    t0 = time.monotonic()
    faults.fire("unit.cap")  # returns at the cap
    assert 0.05 < time.monotonic() - t0 < 5
    faults.clear()


def test_hang_fault_env_parse_and_default_cap():
    n = faults.load_env("a.site:hang:1.0:2:0.25,b.site:hang")
    assert n == 2
    assert faults.armed() == {"a.site": "hang", "b.site": "hang"}
    with faults._lock:
        assert faults._armed["a.site"].delay_s == 0.25
        assert faults._armed["b.site"].delay_s == faults.HANG_CAP_S
        assert faults._armed["a.site"].release is not None
    faults.clear()


def test_clear_releases_all_hangers():
    faults.arm("u.one", "hang", delay_s=30)
    faults.arm("u.two", "hang", delay_s=30)
    done = []
    ts = [
        threading.Thread(target=lambda s=s: (faults.fire(s), done.append(s)),
                         daemon=True)
        for s in ("u.one", "u.two")
    ]
    for t in ts:
        t.start()
    time.sleep(0.05)
    faults.clear()
    for t in ts:
        t.join(5)
    assert sorted(done) == ["u.one", "u.two"]


# ===================================================================
# Differential identity under a hung device plane (both engines)
# ===================================================================


def _zk_env(zk_pp, pipeline):
    net = Network(
        RequestValidator(ZKATDLogDriver(zk_pp)),
        policy=BlockPolicy(max_block_txs=8, min_batch=2, pipeline=pipeline),
    )
    parties = {
        name: Party(name, ZKATDLogDriver(zk_pp), net)
        for name in ("issuer-node", "alice-node", "bob-node")
    }
    issuer = parties["issuer-node"].new_issuer_wallet("issuer")
    alice = parties["alice-node"].new_owner_wallet("alice", anonymous=False)
    bob = parties["bob-node"].new_owner_wallet("bob", anonymous=False)
    if hasattr(getattr(net.validator.driver, "pp", None), "add_issuer"):
        net.validator.driver.pp.add_issuer(issuer.identity)
    return net, parties, alice, bob


def _zk_transfer_block(zk_pp, pipeline):
    """One committed zk block of 2 same-shape transfers; returns
    (statuses, bob_balance) — the differential unit."""
    net, parties, alice, bob = _zk_env(zk_pp, pipeline)
    tx = Transaction(parties["issuer-node"], "seed")
    tx.issue("issuer", "USD", [5, 5],
             [alice.recipient_identity()] * 2, anonymous=False)
    tx.collect_endorsements(None)
    tx.submit()
    alice_p = parties["alice-node"]
    reqs = []
    for i, tid in enumerate(alice_p.vault.token_ids()):
        req = alice_p.tms.new_request(f"pay-{i}")
        tokens, metas = alice_p.vault.get_many([tid])
        alice_p.tms.add_transfer(
            req, [tid], tokens, metas, "USD", [5], [bob.recipient_identity()]
        )
        alice_p.tms.sign_transfers(req)
        reqs.append(req)
    events = net.submit_many([r.to_bytes() for r in reqs])
    return (
        [e.status for e in events],
        parties["bob-node"].balance("USD"),
    )


@pytest.mark.parametrize("pipeline", [True, False])
def test_hang_fault_commits_via_host_fallback_same_verdicts(
    zk_pp, pipeline, monkeypatch
):
    """Acceptance: with `hang` injected at `batch.verify`, the block
    commits via host fallback within FTS_DEVICE_DEADLINE_S + slack (no
    indefinite stall), verdicts identical to the fault-free run — on
    BOTH block engines — and the timeout is visible in the resilience
    counters."""
    resilience.reset()
    deadline_s = 0.5
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_VERIFY_S", str(deadline_s))
    to0 = _counter("resilience.bounded.timeouts")
    be0 = _counter("ledger.block.batch_errors")
    host0 = _counter("ledger.validate.host")
    faults.arm("batch.verify", "hang", count=1, delay_s=60)
    t0 = time.monotonic()
    try:
        injected = _zk_transfer_block(zk_pp, pipeline)
    finally:
        faults.disarm("batch.verify")  # release the abandoned worker
    wall = time.monotonic() - t0
    # bounded: the block resolved at the deadline, nowhere near the
    # 60s hang cap (generous slack for the host re-validate + CI noise)
    assert wall < 30, f"hung block took {wall:.1f}s"
    assert _counter("resilience.bounded.timeouts") - to0 == 1
    assert _counter("ledger.block.batch_errors") - be0 == 1
    assert _counter("ledger.validate.host") - host0 == 2  # host re-verified
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_VERIFY_S", "0")
    resilience.reset()  # clean-run breaker must start fresh
    clean = _zk_transfer_block(zk_pp, pipeline)
    assert injected == clean == ([TxStatus.VALID, TxStatus.VALID], 10)


@pytest.mark.parametrize("pipeline", [True, False])
def test_straggler_worker_does_not_double_apply(zk_pp, pipeline, monkeypatch):
    """An abandoned verify worker that completes AFTER host fallback
    already resolved the block (hang released at its cap, then the
    device verify runs to completion) must not double-apply verdicts or
    corrupt block metrics — on BOTH engines."""
    resilience.reset()
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_VERIFY_S", "0.15")
    s0 = _counter("resilience.bounded.stragglers")
    valid0 = _counter("network.tx.valid")
    batched0 = _counter("ledger.validate.batched")
    blocks0 = _counter("ledger.blocks.committed")
    devtxs0 = _counter("batch.transfer.txs")
    # cap 0.5s: the worker outlives the 0.15s deadline (abandoned), then
    # completes the REAL device verify in the background
    faults.arm("batch.verify", "hang", count=1, delay_s=0.5)
    try:
        statuses, bob_balance = _zk_transfer_block(zk_pp, pipeline)
    finally:
        faults.disarm("batch.verify")
    assert statuses == [TxStatus.VALID, TxStatus.VALID]
    assert bob_balance == 10
    valid_after = _counter("network.tx.valid") - valid0
    blocks_after = _counter("ledger.blocks.committed") - blocks0
    # wait for the straggler to finish its discarded device verify
    deadline = time.monotonic() + 30
    while (
        _counter("resilience.bounded.stragglers") == s0
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    assert _counter("resilience.bounded.stragglers") - s0 == 1
    time.sleep(0.1)  # anything the straggler would corrupt has landed
    # no verdict was double-applied: tx/block counters unchanged by the
    # straggler, and its discarded verdicts never count as batched
    assert _counter("network.tx.valid") - valid0 == valid_after
    assert _counter("ledger.blocks.committed") - blocks0 == blocks_after
    assert _counter("ledger.validate.batched") - batched0 == 0
    # the discarded device verify must not report its txs as device-
    # served either (counted-on-completion contract, straggler-aware)
    assert _counter("batch.transfer.txs") - devtxs0 == 0


# ===================================================================
# Sign plane: transient construction failure heals (latch replacement)
# ===================================================================


def _pk_chain(n_transfers):
    pp = FabTokenPublicParams()
    key = sign.keygen(random.Random(7))
    ident = identity.pk_identity(key.public)
    drv = FabTokenDriver(pp)
    reqs = []
    out = drv.issue(ident, "USD", [9], [ident])
    req = TokenRequest(anchor="seed")
    req.issues.append(
        IssueRecord(action=out.action_bytes, issuer=ident,
                    outputs_metadata=out.metadata, receivers=[ident])
    )
    req.issues[0].signature = key.sign(req.marshal_to_sign(), random.Random(11))
    reqs.append(req.to_bytes())
    prev, prev_raw = ID("seed", 0), out.outputs[0]
    for k in range(n_transfers):
        t = drv.transfer([prev], [prev_raw], [prev_raw], "USD", [9], [ident])
        tr = TokenRequest(anchor=f"t{k}")
        tr.transfers.append(
            TransferRecord(action=t.action_bytes, input_ids=[prev],
                           senders=[ident], outputs_metadata=t.metadata,
                           receivers=[ident])
        )
        tr.transfers[0].signatures = [
            key.sign(tr.marshal_to_sign(), random.Random(100 + k))
        ]
        reqs.append(tr.to_bytes())
        prev, prev_raw = ID(f"t{k}", 0), t.outputs[0]
    return pp, reqs


def test_sign_plane_transient_construction_failure_heals():
    """Regression for the PR-14 latch: a TRANSIENT verifier construction
    failure (one-off OOM) must not disable device signatures for the
    process lifetime. The breaker opens (host fallback, collection
    skipped), and once the cooldown expires the half-open probe
    re-constructs and RE-ENGAGES the device plane."""
    from fabric_token_sdk_tpu.crypto import batch_sign as bs_module

    pp, reqs = _pk_chain(6)
    chunks = [reqs[0:3], reqs[3:5], reqs[5:7]]  # >= 2 pk obligations each
    net = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(
            max_block_txs=16, sign_batched=True, sign_min_batch=2
        ),
    )
    resilience.reset()
    brk = resilience.breaker("sign")
    brk.failure_threshold = 1  # one construction failure opens it
    # generous vs the ms-fast fabtoken blocks: chunk 2 must land INSIDE
    # the cooldown window or it would become the probe itself
    brk.cooldown_s = 1.5

    fb0 = _counter("batch.sign.host_fallbacks")
    rows0 = _counter("batch.sign.rows")
    real = bs_module.BatchedSchnorrVerifier

    class _Boom:
        def __init__(self, *a, **k):
            raise MemoryError("transient construction OOM")

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(bs_module, "BatchedSchnorrVerifier", _Boom)
        ev1 = net.submit_many(chunks[0])
        assert all(e.status == TxStatus.VALID for e in ev1)  # host verified
        assert _counter("batch.sign.host_fallbacks") - fb0 == 3
        assert brk.state == "open"
        # while open: collection is skipped entirely (the latch's fast
        # path, preserved) — no new fallback counts, still all-Valid
        ev2 = net.submit_many(chunks[1])
        assert all(e.status == TxStatus.VALID for e in ev2)
        assert _counter("batch.sign.host_fallbacks") - fb0 == 3
        assert _counter("batch.sign.rows") == rows0
    assert bs_module.BatchedSchnorrVerifier is real
    time.sleep(1.6)  # cooldown expires -> half-open probe due
    ev3 = net.submit_many(chunks[2])
    assert all(e.status == TxStatus.VALID for e in ev3)
    # the probe re-constructed the verifier and the rows rode the device
    assert _counter("batch.sign.rows") - rows0 == 2
    assert brk.state == "closed"


# ===================================================================
# Surfacing: ftstop breaker column
# ===================================================================


def test_ftstop_renders_breaker_column():
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "cmd")
    )
    try:
        import ftstop
    finally:
        sys.path.pop(0)
    health = {"uptime_s": 1.0, "height": 3,
              "breakers": {"verify": "closed", "sign": "closed"}}
    assert "brk=ok" in ftstop.format_row(health, {}, None, None)
    health["breakers"]["sign"] = "open"
    health["breakers"]["stages"] = "half-open"
    row = ftstop.format_row(health, {}, None, None)
    assert "brk=sign:open,stages:half-open" in row
    # nodes predating the field render no column at all
    row_old = ftstop.format_row({"uptime_s": 1.0, "height": 3}, {}, None, None)
    assert "brk=" not in row_old


def test_health_serves_breaker_states(zk_pp):
    resilience.reset()
    resilience.breaker("verify").record_failure()
    net = Network(RequestValidator(ZKATDLogDriver(zk_pp)))
    h = net.health()
    assert h["breakers"] == {"verify": "closed"}


# ===================================================================
# Bench chaos soak (FTS_BENCH_SOAK_FAULTS=1) smoke
# ===================================================================


def test_bench_chaos_soak_smoke(monkeypatch):
    """The chaos-soak mode end to end (tiny budget): randomized injected
    faults for the whole window, the node stays live with every
    acknowledged tx Valid, and the soak section is schema-valid with the
    resilience fields present."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    try:
        import bench
    finally:
        sys.path.pop(0)
    from fabric_token_sdk_tpu.utils import benchschema

    monkeypatch.setenv("FTS_BENCH_SOAK_S", "1.5")
    monkeypatch.setenv("FTS_BENCH_SOAK_CLIENTS", "2")
    monkeypatch.setenv("FTS_BENCH_SOAK_GROUP", "4")
    monkeypatch.setenv("FTS_BENCH_SOAK_QUEUE_MAX", "16")
    monkeypatch.setenv("FTS_BENCH_SOAK_FAULTS", "1")
    # pin the deadline ourselves so _soak's setdefault (a process-level
    # knob in a real bench run) is monkeypatch-scoped and restored here
    monkeypatch.setenv("FTS_DEVICE_DEADLINE_S", "1")

    class _HB:
        def set_phase(self, *a, **k):
            pass

    soak = bench._soak(_HB())
    assert benchschema.validate_soak(soak) == []
    # every acknowledged tx was Valid (the soak client asserts per
    # batch and _soak re-raises) and the node stayed live throughout
    assert soak["steady_txs_per_s"] > 0
    assert soak["txs"] > 0
    # resilience fields are present (ints; the fabtoken corpus has no
    # batchable device groups, so breaker trips may legitimately be 0)
    for key in ("faults_injected", "breaker_trips", "degraded_planes"):
        assert isinstance(soak[key], int) and soak[key] >= 0
    assert not faults.armed()  # the monkey disarmed everything
