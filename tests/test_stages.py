"""Differential tests: stage tile kernels vs pure-host group math.

Every primitive stage in `ops/stages.py` is pinned against
`crypto/hostmath.py` on random inputs, including padding edges (batch
sizes that are not ROW_TILE multiples) and the host-glue helpers.
"""

import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.ops import curve as cv, curve2 as cv2, limbs as lb, \
    stages as st, tower as tw
from fabric_token_sdk_tpu.ops import pairing as pr
from fabric_token_sdk_tpu.utils import metrics as mx

_RINV = pow(1 << (lb.RADIX_BITS * lb.NLIMBS), -1, hm.P)


def _decode_affine_g1(aff):
    """(N, 2, L) Montgomery affine limbs -> host (x, y) tuples."""
    return [
        (lb.limbs_to_int(row[0]) * _RINV % hm.P,
         lb.limbs_to_int(row[1]) * _RINV % hm.P)
        for row in aff
    ]


def _g1_jac(pts):
    return np.stack([cv.encode_point(p) for p in pts])


def _scalars(rng, n):
    return [rng.randrange(hm.R) for _ in range(n)]


def test_g1_mul_rows_matches_host(rng):
    pts = [hm.g1_mul(hm.G1_GEN, 3 + i) for i in range(5)]  # odd: pads to 8
    ks = _scalars(rng, 5)
    got = st.g1_mul_rows(_g1_jac(pts), cv.encode_scalars(ks))
    assert cv.decode_points(got) == [hm.g1_mul(p, k) for p, k in zip(pts, ks)]


def test_g1_add_sub_rows_match_host(rng):
    ps = [hm.g1_mul(hm.G1_GEN, 3 + i) for i in range(9)]
    qs = [hm.g1_mul(hm.G1_GEN, 100 + i) for i in range(9)]
    got = st.g1_add_rows(_g1_jac(ps), _g1_jac(qs))
    assert cv.decode_points(got) == [hm.g1_add(p, q) for p, q in zip(ps, qs)]
    got = st.g1_sub_rows(_g1_jac(ps), _g1_jac(qs))
    assert cv.decode_points(got) == [
        hm.g1_add(p, hm.g1_neg(q)) for p, q in zip(ps, qs)
    ]
    # edge rows: P - P = infinity, P + (-P) handled by the select logic
    got = st.g1_sub_rows(_g1_jac(ps[:2]), _g1_jac(ps[:2]))
    assert cv.decode_points(got) == [None, None]


def test_g1_msm_rows_matches_host_multiexp(rng):
    bases = [hm.g1_mul(hm.G1_GEN, 11 + i) for i in range(3)]
    table = cv.FixedBaseTable(bases)
    rows = [_scalars(rng, 3) for _ in range(6)]
    got = st.g1_msm_rows(table.flat, np.stack([cv.encode_scalars(r) for r in rows]))
    assert cv.decode_points(got) == [hm.g1_multiexp(bases, r) for r in rows]


def test_g1_to_affine_rows_matches_decode(rng):
    pts = [hm.g1_mul(hm.G1_GEN, 5 + i) for i in range(3)]
    ks = cv.encode_scalars(_scalars(rng, 3))
    jac = st.g1_mul_rows(_g1_jac(pts), ks)  # non-trivial Z coordinates
    aff = st.g1_to_affine_rows(jac)
    # affine limbs must decode to the same canonical points
    assert _decode_affine_g1(aff) == cv.decode_points(jac)


def test_affine_to_jac_np_round_trips():
    pts = [hm.g1_mul(hm.G1_GEN, 7 + i) for i in range(4)]
    aff = np.asarray(pr.encode_g1(pts))
    jac = st.affine_to_jac_np(aff)
    assert jac.shape == (4, 3, aff.shape[-1])
    assert cv.decode_points(jac) == pts


def test_run_rows_empty_batch_raises():
    with pytest.raises(ValueError):
        st.run_rows(cv.add, np.zeros((0, 3, 32), np.int32),
                    np.zeros((0, 3, 32), np.int32))


def test_run_rows_counts_transfers(rng):
    before = mx.REGISTRY.counter("batch.tiled.transfers").value
    ps = _g1_jac([hm.g1_mul(hm.G1_GEN, 2 + i) for i in range(9)])
    st.g1_add_rows(ps, ps)  # 9 rows -> 2 tiles x 2 arrays = 4 transfers
    assert mx.REGISTRY.counter("batch.tiled.transfers").value - before == 4


def test_run_rows_dp_edge_cases_match_host(rng):
    """Sharded runner edges at the stage level: ntiles < dp, dp == 1
    no-op (no sharded counters), dp == ntiles, and a consts-carrying
    kernel (msm) — all bit-identical to the unsharded walk and correct
    vs host math."""
    pts = [hm.g1_mul(hm.G1_GEN, 5 + i) for i in range(9)]  # 2 ragged tiles
    ks = _scalars(rng, 9)
    expected = _g1_jac([hm.g1_mul(p, k) for p, k in zip(pts, ks)])
    base = st.g1_mul_rows(_g1_jac(pts), cv.encode_scalars(ks))
    sharded_before = mx.REGISTRY.counter("stages.sharded_calls").value
    one = st.g1_mul_rows(_g1_jac(pts), cv.encode_scalars(ks), dp=1)
    assert (
        mx.REGISTRY.counter("stages.sharded_calls").value == sharded_before
    ), "dp=1 must stay on the unsharded walk"
    got = st.g1_mul_rows(_g1_jac(pts), cv.encode_scalars(ks), dp=8)
    assert np.array_equal(got, base)  # dp > ntiles: one tile per shard
    assert np.array_equal(one, base)
    assert cv.decode_points(base) == cv.decode_points(expected)
    # consts (window table) reach every shard of an msm dispatch
    bases = [hm.g1_mul(hm.G1_GEN, 7 + i) for i in range(2)]
    from fabric_token_sdk_tpu.crypto.pedersen import BatchedPedersen

    ped = BatchedPedersen(bases)
    rows = [[rng.randrange(hm.R), rng.randrange(hm.R)] for _ in range(9)]
    host = [hm.g1_multiexp(bases, r) for r in rows]
    assert ped.commit_ints(rows, dp=4)[0] == host


def test_gt_is_one_host():
    one = tw.fp12_one_np()
    not_one = tw.encode_fp12([((2, 0), (0, 0), (0, 0), (0, 0), (0, 0), (0, 0))])[0]
    got = pr.gt_is_one_host(np.stack([one, not_one]))
    assert got.tolist() == [True, False]
    assert pr.gt_is_one_host(np.zeros((0, 6, 2, 32), np.int32)).tolist() == []


@pytest.mark.slow
def test_g1_msm_rows_one_and_two_bases(rng):
    for nb in (1, 2):
        bases = [hm.g1_mul(hm.G1_GEN, 17 + i) for i in range(nb)]
        table = cv.FixedBaseTable(bases)
        rows = [_scalars(rng, nb) for _ in range(3)]
        got = st.g1_msm_rows(
            table.flat, np.stack([cv.encode_scalars(r) for r in rows])
        )
        assert cv.decode_points(got) == [hm.g1_multiexp(bases, r) for r in rows]


@pytest.mark.slow
def test_g2_stage_rows_match_host(rng):
    pts = [hm.g2_mul(hm.G2_GEN, 3 + i) for i in range(5)]
    ks = _scalars(rng, 5)
    jac = np.asarray(cv2.encode_points(pts))
    got = st.g2_mul_rows(jac, cv.encode_scalars(ks))
    assert cv2.decode_points(got) == [hm.g2_mul(p, k) for p, k in zip(pts, ks)]

    qs = [hm.g2_mul(hm.G2_GEN, 50 + i) for i in range(5)]
    got = st.g2_add_rows(jac, np.asarray(cv2.encode_points(qs)))
    assert cv2.decode_points(got) == [hm.g2_add(p, q) for p, q in zip(pts, qs)]

    # tree sum over k=3 terms per row
    terms = np.stack(
        [np.asarray(cv2.encode_points([p, q, hm.G2_GEN]))
         for p, q in zip(pts, qs)]
    )
    got = st.g2_tree_sum_rows(terms)
    assert cv2.decode_points(got) == [
        hm.g2_add(hm.g2_add(p, q), hm.G2_GEN) for p, q in zip(pts, qs)
    ]

    aff = st.g2_to_affine_rows(jac)
    assert aff.shape == (5, 2, 2, jac.shape[-1])
    # affine coordinates decode to the same host points
    coords = tw.decode_fp2(aff.reshape(-1, 2, jac.shape[-1]))
    decoded = [(coords[2 * i], coords[2 * i + 1]) for i in range(5)]
    assert decoded == pts
