"""Sharded dispatch on the 8-virtual-device CPU mesh == unsharded results."""
import os
import random

import jax
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.ops import curve as cv, stages as st
from fabric_token_sdk_tpu.parallel import (
    MeshConfig,
    make_mesh,
    mesh_dp,
    run_rows_dp,
    shard_rows,
    sharded_schnorr_rows,
)
from fabric_token_sdk_tpu.utils import metrics as mx


def _counter(name):
    return mx.REGISTRY.counter(name).value


def test_mesh_shapes():
    assert len(jax.devices()) == 8
    mesh = make_mesh(8, mp=2)
    assert mesh.shape == {"dp": 4, "mp": 2}
    assert mesh_dp(mesh) == 4
    # a non-dividing mp is CLAMPED to the largest divisor, not rejected —
    # an odd mesh request can never knock a node off the sharded path
    before = _counter("sharding.clamped")
    mesh = make_mesh(8, mp=3)
    assert mesh.shape == {"dp": 4, "mp": 2}
    assert _counter("sharding.clamped") - before == 1


def test_mesh_config_build_and_of():
    cfg = MeshConfig.build(8, 2)
    assert (cfg.n_devices, cfg.dp, cfg.mp, cfg.workers) == (8, 4, 2, 8)
    before = _counter("sharding.clamped")
    cfg = MeshConfig.build(6, 4)  # 4 does not divide 6 -> clamp to 3
    assert (cfg.dp, cfg.mp) == (2, 3)
    assert _counter("sharding.clamped") - before == 1
    # coercion: a jax Mesh, a MeshConfig, and None all round-trip
    assert MeshConfig.of(make_mesh(8, mp=2)) == MeshConfig(8, 4, 2)
    assert MeshConfig.of(cfg) is cfg
    assert MeshConfig.of(None) is None
    assert mesh_dp(cfg) == 2 and mesh_dp(None) is None


def test_mesh_config_from_env(monkeypatch):
    monkeypatch.delenv("FTS_MESH_DEVICES", raising=False)
    assert MeshConfig.from_env() is None
    assert st.default_dp() == 1 and st.default_mp() == 1
    monkeypatch.setenv("FTS_MESH_DEVICES", "8")
    monkeypatch.setenv("FTS_MESH_MP", "2")
    assert MeshConfig.from_env() == MeshConfig(8, 4, 2)
    assert st.default_dp() == 4 and st.default_mp() == 2
    # FTS_DP_SHARDS wins over the mesh env for the row runner
    monkeypatch.setenv("FTS_DP_SHARDS", "3")
    assert st.default_dp() == 3
    # garbage env degrades to unsharded, never raises
    monkeypatch.setenv("FTS_DP_SHARDS", "zap")
    monkeypatch.setenv("FTS_MESH_DEVICES", "zap")
    assert st.default_dp() == 1 and st.default_mp() == 1


def test_shard_rows_pads_ragged_batch():
    """B % dp != 0 pads rows to the span boundary (counted) instead of
    erroring; the placed array keeps the padded leading extent."""
    mesh = make_mesh(8, mp=2)  # dp=4
    rng = random.Random(3)
    pts = np.stack([cv.encode_point(hm.rand_g1(rng)) for _ in range(5)])
    before = _counter("sharding.padded_rows")
    placed = shard_rows(pts, mesh)
    assert placed.shape[0] == 8  # 5 -> next dp=4 boundary
    assert _counter("sharding.padded_rows") - before == 3
    got = np.asarray(placed)
    assert np.array_equal(got[:5], pts)
    assert np.array_equal(got[5:], np.broadcast_to(pts[:1], (3,) + pts.shape[1:]))
    # an aligned batch is placed untouched
    before = _counter("sharding.padded_rows")
    assert shard_rows(pts[:4], mesh).shape[0] == 4
    assert _counter("sharding.padded_rows") - before == 0


def test_run_rows_sharded_failure_degrades_to_unsharded(rng, monkeypatch):
    """Degrade chain, first link: a sharded-dispatch crash falls back to
    the unsharded runner with identical output (`sharding.fallbacks`)."""
    pts = np.stack([cv.encode_point(hm.rand_g1(rng)) for _ in range(11)])
    expected = st.g1_add_rows(pts, pts)

    def boom(*a, **k):
        raise RuntimeError("injected sharded-dispatch failure")

    # break the span partitioner INSIDE run_tile_spans' guarded region:
    # the dispatch crashes, the sequential walk must still answer
    monkeypatch.setattr(st, "dp_spans", boom)
    before = _counter("sharding.fallbacks")
    got = st.g1_add_rows(pts, pts, dp=4)
    assert _counter("sharding.fallbacks") - before == 1
    assert np.array_equal(got, expected)


def test_dp_spans_are_tile_aligned_and_cover():
    """The per-shard dispatch partitions the tile range exactly: spans
    are contiguous, non-overlapping, and never exceed the shard count."""
    for ntiles in (1, 2, 3, 7, 8, 13):
        for dp in (1, 2, 4, 8, 32):
            spans = st.dp_spans(ntiles, dp)
            assert len(spans) == min(dp, ntiles)
            assert spans[0][0] == 0 and spans[-1][1] == ntiles
            for (a, b), (c, _) in zip(spans, spans[1:]):
                assert a < b == c
    # edge cases pinned explicitly: ntiles < dp collapses to one tile per
    # span; dp=1 is the no-op identity span; uneven ntiles front-loads
    assert st.dp_spans(3, 8) == [(0, 1), (1, 2), (2, 3)]
    assert st.dp_spans(13, 1) == [(0, 13)]
    assert st.dp_spans(13, 4) == [(0, 4), (4, 7), (7, 10), (10, 13)]


def _kernel_cases(rng, N, heavy: bool):
    """(name, fn(dp)) pairs covering every stage kernel; the two
    variable-base scalar-mul tiles (~10-20s per warm dispatch on a
    small CPU host) are the `heavy` subset, exercised by the
    slow-marked full-matrix test so tier-1 stays in budget."""
    L = 32
    g1 = np.stack([cv.encode_point(hm.rand_g1(rng)) for _ in range(N)])
    g1b = np.stack([cv.encode_point(hm.rand_g1(rng)) for _ in range(N)])
    scal = np.asarray(cv.encode_scalars(
        [rng.randrange(hm.R) for _ in range(N)]
    ))
    from fabric_token_sdk_tpu.ops import curve2 as cv2

    g2pts = [hm.rand_g2(rng) for _ in range(2)]
    g2 = np.asarray(cv2.encode_points(
        [g2pts[i % 2] for i in range(N)]
    ))
    g2b = np.asarray(cv2.encode_points(
        [g2pts[(i + 1) % 2] for i in range(N)]
    ))
    from fabric_token_sdk_tpu.crypto.pedersen import BatchedPedersen

    ped = BatchedPedersen([hm.rand_g1(rng) for _ in range(3)])
    msm_scal = np.asarray(
        cv.encode_scalars(
            [rng.randrange(hm.R) for _ in range(3 * N)]
        )
    ).reshape(N, 3, L)
    if heavy:
        return [
            ("g1_mul", lambda dp: st.g1_mul_rows(g1, scal, dp=dp)),
            ("g2_mul", lambda dp: st.g2_mul_rows(g2, scal, dp=dp)),
        ]
    return [
        ("g1_msm", lambda dp: ped.commit_rows(msm_scal, dp=dp)),
        ("g1_add", lambda dp: st.g1_add_rows(g1, g1b, dp=dp)),
        ("g1_sub", lambda dp: st.g1_sub_rows(g1, g1b, dp=dp)),
        ("g1_to_affine", lambda dp: st.g1_to_affine_rows(g1, dp=dp)),
        ("g2_add", lambda dp: st.g2_add_rows(g2, g2b, dp=dp)),
        ("g2_to_affine", lambda dp: st.g2_to_affine_rows(g2, dp=dp)),
    ]


def test_stage_kernels_sharded_bit_identity(rng):
    """Satellite acceptance: dp-sharded dispatch is bit-identical to the
    unsharded runner, per stage kernel, on a ragged batch (uneven
    spans). The two variable-base mul tiles are covered by the
    slow-marked full matrix below (their sharded parity ALSO runs
    non-slow inside `test_sharded_schnorr_rows_matches_host` and the
    sharded verifier/prover differentials); dp > ntiles and
    span-partition edges by `test_dp_spans_are_tile_aligned_and_cover` /
    `test_run_rows_dp_parity`."""
    for name, fn in _kernel_cases(rng, 11, heavy=False):
        assert np.array_equal(fn(3), fn(1)), name


@pytest.mark.slow
def test_every_stage_kernel_sharded_bit_identity_matrix(rng):
    """Full matrix: EVERY stage kernel (heavy muls included) across
    several dp extents, incl. dp > ntiles."""
    for heavy in (False, True):
        for name, fn in _kernel_cases(rng, 11, heavy=heavy):
            base = fn(1)
            for dp in (2, 3, 8):
                assert np.array_equal(fn(dp), base), (name, dp)


def test_sharded_schnorr_rows_matches_host(rng):
    """Per-shard stage-tile dispatch of the Schnorr reconstruction (the
    WF verify composition) over dp == host math, and sharding compiles
    ZERO new programs (same canonical tile executables)."""
    bases = [hm.rand_g1(rng) for _ in range(3)]
    table = cv.FixedBaseTable(bases)
    mesh = make_mesh(8, mp=2)  # dp=4
    N = 18  # 3 tiles of 8 rows (padded) split across 4 dp shards
    resp = np.zeros((N, 3, 32), dtype=np.int32)
    stmt = np.zeros((N, 3, 32), dtype=np.int32)
    chal = np.zeros((N, 32), dtype=np.int32)
    expected = []
    for i in range(N):
        c = rng.randrange(hm.R)
        zs = [rng.randrange(hm.R) for _ in range(3)]
        pt = hm.rand_g1(rng)
        chal[i] = np.asarray(cv.encode_scalars([c]))[0]
        stmt[i] = cv.encode_point(pt)
        resp[i] = np.asarray(cv.encode_scalars(zs))
        expected.append(
            hm.g1_add(hm.g1_multiexp(bases, zs), hm.g1_neg(hm.g1_mul(pt, c)))
        )
    # warm the tiles (may compile on a cold cache), then pin zero-new
    unsharded = sharded_schnorr_rows(table, resp, stmt, chal, mesh=None)
    compiles = "jax.core.compile.backend_compile_duration.seconds"
    before = mx.REGISTRY.histogram(compiles).count
    sharded_before = mx.REGISTRY.counter("stages.sharded_calls").value
    out = sharded_schnorr_rows(table, resp, stmt, chal, mesh)
    assert mx.REGISTRY.histogram(compiles).count - before == 0, (
        "dp sharding compiled a new program -- the per-shard dispatch must "
        "reuse the canonical tile executables"
    )
    assert mx.REGISTRY.counter("stages.sharded_calls").value > sharded_before
    assert cv.decode_points(out) == expected
    assert cv.decode_points(unsharded) == expected


def test_run_rows_dp_parity(rng):
    """run_rows_dp over any dp equals the unsharded stage runner."""
    pts = np.stack(
        [cv.encode_point(hm.rand_g1(rng)) for _ in range(11)]
    )
    base = st.g1_add_rows(pts, pts)
    for dp in (2, 3, 8):
        got = run_rows_dp(cv.add, pts, pts, dp=dp)
        assert np.array_equal(got, base)


@pytest.fixture(scope="module")
def zk_pp():
    from fabric_token_sdk_tpu.crypto.setup import setup

    return setup(base=4, exponent=2, rng=random.Random(0xF75))


@pytest.fixture(scope="module")
def zk_prover(zk_pp):
    """One prover per module — window tables are the expensive part;
    the mesh is re-bound per test via set_mesh (dispatch state only)."""
    from fabric_token_sdk_tpu.crypto.batch_prove import BatchedTransferProver

    return BatchedTransferProver(zk_pp)


def _wf_reqs(zk_pp, rng, n):
    """n (1,1)-shape witness/commitment requests (WF-only: non-slow)."""
    from fabric_token_sdk_tpu.crypto import token as tok

    reqs = []
    for _ in range(n):
        it, iw = tok.tokens_with_witness([7], "USD", zk_pp.ped_params, rng)
        ot, ow = tok.tokens_with_witness([7], "USD", zk_pp.ped_params, rng)
        reqs.append((iw, ow, it, ot))
    return reqs


def test_sharded_verifier_verdicts_bit_identical(zk_pp, zk_prover, rng):
    """Tentpole acceptance: the mesh-sharded `BatchedTransferVerifier`
    returns BIT-IDENTICAL verdicts to the unsharded one — valid rows AND
    a tampered row (sharding shards dispatch, never semantics). One
    verifier instance, mesh re-bound via `set_mesh` (tables are built
    once; the mesh is dispatch state)."""
    from fabric_token_sdk_tpu.crypto.batch import BatchedTransferVerifier

    reqs = _wf_reqs(zk_pp, rng, 5)
    zk_prover.set_mesh(None)
    proofs = zk_prover.prove(reqs, random.Random(11))
    bad = bytearray(proofs[2])
    bad[len(bad) // 2] ^= 1
    proofs[2] = bytes(bad)
    txs = [(r[2], r[3], p) for r, p in zip(reqs, proofs)]

    verifier = BatchedTransferVerifier(zk_pp)
    plain = verifier.verify(txs)
    before = _counter("stages.sharded_calls")
    verifier.set_mesh(MeshConfig.build(8, 2))
    assert verifier.wf.mesh == MeshConfig(8, 4, 2)  # propagated
    sharded = verifier.verify(txs)
    assert _counter("stages.sharded_calls") > before
    assert np.array_equal(plain, sharded)
    assert sharded.tolist() == [True, True, False, True, True]


def test_sharded_prover_proofs_byte_identical(zk_pp, zk_prover, rng):
    """The mesh-sharded `BatchedTransferProver` emits byte-identical
    proofs (same draws, same transcripts — dp only partitions the
    commit-phase dispatch), and `set_mesh` re-binds a live instance."""
    reqs = _wf_reqs(zk_pp, rng, 3)
    zk_prover.set_mesh(None)
    plain = zk_prover.prove(reqs, random.Random(42))
    zk_prover.set_mesh(MeshConfig.build(8, 2))
    assert plain == zk_prover.prove(reqs, random.Random(42))
    zk_prover.set_mesh(None)
    assert plain == zk_prover.prove(reqs, random.Random(42))


@pytest.mark.slow
def test_sharded_pairing_product_staged_parity(rng):
    """dp x mp staged pairing dispatch == unsharded staged == host math,
    on a ragged batch (B=5 over dp=4)."""
    from fabric_token_sdk_tpu.crypto import pssign
    from fabric_token_sdk_tpu.ops import pairing as pr
    from fabric_token_sdk_tpu.parallel import sharded_pairing_product

    mesh = make_mesh(8, mp=2)
    signer = pssign.keygen(1, rng)
    B = 5
    msgs = [[rng.randrange(100)] for _ in range(B)]
    sigs = [signer.sign(m, rng) for m in msgs]
    Ps = np.stack([
        pr.encode_g1([hm.g1_neg(s.S), s.R]) for s in sigs
    ])
    Qs = np.stack([
        pr.encode_g2([signer.Q, signer.message_base(m)]) for m in msgs
    ])
    plain = pr.pairing_product_staged(Ps, Qs, dp=1, mp=1)
    before = _counter("pairing.staged.sharded_calls")
    sharded = sharded_pairing_product(Ps, Qs, mesh)
    assert _counter("pairing.staged.sharded_calls") > before
    assert np.array_equal(plain, sharded)
    assert pr.gt_is_one_host(sharded).all()


def test_multichip_deadline_emits_degraded_result(tmp_path):
    """Satellite acceptance: a dry run that blows its deadline leaves a
    PARSED `MULTICHIP.result.json` (ok=false, degraded, live phase) and
    the flight sidecar — never a silent rc=124."""
    import json
    import subprocess
    import sys as _sys

    sidecar = tmp_path / "MULTICHIP.metrics.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # the child must see itself as a STANDALONE entry point (watchdog,
    # sidecars) — not as running inside this pytest process
    env.pop("PYTEST_CURRENT_TEST", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "_FTS_TPU_REEXEC": "1",  # no clean-subprocess delegation
        "FTS_MULTICHIP_DEADLINE": "2",
        "FTS_METRICS_SIDECAR": str(sidecar),
    })
    proc = subprocess.run(
        [_sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py"),
         "--dryrun", "8"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 1, proc.stderr[-2000:]
    result_path = tmp_path / "MULTICHIP.result.json"
    assert result_path.exists(), proc.stderr[-2000:]
    doc = json.loads(result_path.read_text())
    assert doc["ok"] is False and doc["degraded"] is True
    assert doc["n_devices"] == 8
    assert isinstance(doc["phase"], str) and doc["phase"]
    assert doc["deadline_s"] == 2.0
    assert (tmp_path / "MULTICHIP.flight.json").exists()
    assert sidecar.exists()


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
