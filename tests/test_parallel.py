"""Sharded dispatch on the 8-virtual-device CPU mesh == unsharded results."""
import random

import jax
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.ops import curve as cv, stages as st
from fabric_token_sdk_tpu.parallel import (
    make_mesh,
    mesh_dp,
    run_rows_dp,
    sharded_schnorr_rows,
)
from fabric_token_sdk_tpu.utils import metrics as mx


def test_mesh_shapes():
    assert len(jax.devices()) == 8
    mesh = make_mesh(8, mp=2)
    assert mesh.shape == {"dp": 4, "mp": 2}
    assert mesh_dp(mesh) == 4
    with pytest.raises(ValueError):
        make_mesh(8, mp=3)


def test_dp_spans_are_tile_aligned_and_cover():
    """The per-shard dispatch partitions the tile range exactly: spans
    are contiguous, non-overlapping, and never exceed the shard count."""
    for ntiles in (1, 2, 3, 7, 8, 13):
        for dp in (1, 2, 4, 8, 32):
            spans = st.dp_spans(ntiles, dp)
            assert len(spans) == min(dp, ntiles)
            assert spans[0][0] == 0 and spans[-1][1] == ntiles
            for (a, b), (c, _) in zip(spans, spans[1:]):
                assert a < b == c


def test_sharded_schnorr_rows_matches_host(rng):
    """Per-shard stage-tile dispatch of the Schnorr reconstruction (the
    WF verify composition) over dp == host math, and sharding compiles
    ZERO new programs (same canonical tile executables)."""
    bases = [hm.rand_g1(rng) for _ in range(3)]
    table = cv.FixedBaseTable(bases)
    mesh = make_mesh(8, mp=2)  # dp=4
    N = 18  # 3 tiles of 8 rows (padded) split across 4 dp shards
    resp = np.zeros((N, 3, 32), dtype=np.int32)
    stmt = np.zeros((N, 3, 32), dtype=np.int32)
    chal = np.zeros((N, 32), dtype=np.int32)
    expected = []
    for i in range(N):
        c = rng.randrange(hm.R)
        zs = [rng.randrange(hm.R) for _ in range(3)]
        pt = hm.rand_g1(rng)
        chal[i] = np.asarray(cv.encode_scalars([c]))[0]
        stmt[i] = cv.encode_point(pt)
        resp[i] = np.asarray(cv.encode_scalars(zs))
        expected.append(
            hm.g1_add(hm.g1_multiexp(bases, zs), hm.g1_neg(hm.g1_mul(pt, c)))
        )
    # warm the tiles (may compile on a cold cache), then pin zero-new
    unsharded = sharded_schnorr_rows(table, resp, stmt, chal, mesh=None)
    compiles = "jax.core.compile.backend_compile_duration.seconds"
    before = mx.REGISTRY.histogram(compiles).count
    sharded_before = mx.REGISTRY.counter("stages.sharded_calls").value
    out = sharded_schnorr_rows(table, resp, stmt, chal, mesh)
    assert mx.REGISTRY.histogram(compiles).count - before == 0, (
        "dp sharding compiled a new program -- the per-shard dispatch must "
        "reuse the canonical tile executables"
    )
    assert mx.REGISTRY.counter("stages.sharded_calls").value > sharded_before
    assert cv.decode_points(out) == expected
    assert cv.decode_points(unsharded) == expected


def test_run_rows_dp_parity(rng):
    """run_rows_dp over any dp equals the unsharded stage runner."""
    pts = np.stack(
        [cv.encode_point(hm.rand_g1(rng)) for _ in range(11)]
    )
    base = st.g1_add_rows(pts, pts)
    for dp in (2, 3, 8):
        got = run_rows_dp(cv.add, pts, pts, dp=dp)
        assert np.array_equal(got, base)


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
