"""Sharded kernels on the 8-virtual-device CPU mesh == unsharded results."""
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.ops import curve as cv
from fabric_token_sdk_tpu.parallel import make_mesh, shard_rows, sharded_wf_verify_kernel


def test_mesh_shapes():
    assert len(jax.devices()) == 8
    mesh = make_mesh(8, mp=2)
    assert mesh.shape == {"dp": 4, "mp": 2}
    with pytest.raises(ValueError):
        make_mesh(8, mp=3)


def test_sharded_schnorr_kernel_matches_host(rng):
    bases = [hm.rand_g1(rng) for _ in range(3)]
    table = cv.FixedBaseTable(bases)
    mesh = make_mesh(8, mp=1)
    B, n = 8, 2
    resp = np.zeros((B, n, 3, 32), dtype=np.int32)
    stmt = np.zeros((B, n, 3, 32), dtype=np.int32)
    chal = np.zeros((B, 32), dtype=np.int32)
    expected = []
    for b in range(B):
        c = rng.randrange(hm.R)
        chal[b] = np.asarray(cv.encode_scalars([c]))[0]
        for j in range(n):
            zs = [rng.randrange(hm.R) for _ in range(3)]
            st = hm.rand_g1(rng)
            stmt[b, j] = cv.encode_point(st)
            resp[b, j] = np.asarray(cv.encode_scalars(zs))
            expected.append(
                hm.g1_add(hm.g1_multiexp(bases, zs), hm.g1_neg(hm.g1_mul(st, c)))
            )
    out = sharded_wf_verify_kernel(
        table, shard_rows(resp, mesh), shard_rows(stmt, mesh),
        shard_rows(chal, mesh), mesh,
    )
    assert cv.decode_points(out) == expected


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
