"""Batched device verification vs host verifiers (slow: pairing compiles)."""
import random
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import batch, hostmath as hm, pssign, sigproof
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.crypto import token as tok, wellformedness as wf


@pytest.fixture(scope="module")
def pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


def test_batched_wf_verify(rng, pp):
    txs = []
    for i in range(3):
        in_toks, in_w = tok.tokens_with_witness([5, 10], "USD", pp.ped_params, rng)
        out_toks, out_w = tok.tokens_with_witness([7, 8], "USD", pp.ped_params, rng)
        raw = wf.TransferWFProver(
            wf.TransferWFWitness(
                "USD",
                [w.value for w in in_w], [w.bf for w in in_w],
                [w.value for w in out_w], [w.bf for w in out_w],
            ),
            pp.ped_params, in_toks, out_toks, rng,
        ).prove()
        txs.append((in_toks, out_toks, raw))
    # tamper the last one
    bad = wf.TransferWF.from_bytes(txs[2][2])
    bad.sum_resp = (bad.sum_resp + 1) % hm.R
    txs[2] = (txs[2][0], txs[2][1], bad.to_bytes())
    verifier = batch.BatchedWFVerifier(pp)
    got = verifier.verify(txs)
    assert got.tolist() == [True, True, False]


@pytest.mark.slow
def test_batched_ps_verify(rng):
    signer = pssign.keygen(1, rng)
    msgs = [[3], [1], [2]]
    sigs = [signer.sign(m, rng) for m in msgs]
    # corrupt one signature
    sigs[1] = pssign.Signature(sigs[1].R, hm.g1_mul(sigs[1].S, 2))
    v = batch.BatchedPSVerifier(signer.pk, signer.Q)
    got = v.verify(msgs, sigs)
    assert got.tolist() == [True, False, True]


@pytest.mark.slow
def test_batched_membership_verify(rng, pp):
    rp = pp.range_params
    proofs, coms = [], []
    for value in (0, 3, 2):
        bf = hm.rand_zr(rng)
        com = hm.g1_multiexp(pp.ped_params[:2], [value, bf])
        w = sigproof.MembershipWitness(rp.signed_values[value], value, bf)
        proofs.append(
            sigproof.MembershipProver(
                w, com, pp.ped_gen, rp.Q, rp.sign_pk, pp.ped_params[:2], rng
            ).prove()
        )
        coms.append(com)
    proofs[2].value_resp = (proofs[2].value_resp + 1) % hm.R
    v = batch.BatchedMembershipVerifier(pp)
    got = v.verify(proofs, coms)
    assert got.tolist() == [True, True, False]
