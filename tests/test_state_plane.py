"""State-plane suite: the pluggable vault stores (in-memory + crash-safe
persistent), the (type, owner) quantity-ordered selection index, the
sharded locker with deadline-aware backoff, the ttxdb integrity fixes —
and the chaos acceptance: a client process SIGKILLed mid-spend-workload
recovers its vault (`Vault.recover`) to exactly the acknowledged-finality
replay, with a torn journal tail truncated and zero leaked selector
locks, under `FTS_FAULTS` injection on the new `vault.*` sites.
"""

import os
import select
import signal
import struct
import subprocess
import sys
import threading
import time
import types

import pytest

from fabric_token_sdk_tpu.drivers.fabtoken import (
    FabTokenDriver,
    FabTokenPublicParams,
)
from fabric_token_sdk_tpu.models.token import ID, Owner, Token
from fabric_token_sdk_tpu.services.selector import (
    InsufficientFunds,
    SelectorManager,
    SelectorTimeout,
    ShardedLocker,
)
from fabric_token_sdk_tpu.services.vault import (
    InMemoryTokenStore,
    PersistentTokenStore,
    Vault,
    VaultDelta,
)
from fabric_token_sdk_tpu.services.vault.store import _Bucket, decoded_token
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx

REPO_ROOT = os.path.join(os.path.dirname(__file__), "..")
OWNER = b"state-test-owner"


def _counter(name):
    return mx.REGISTRY.counter(name).value


def _driver():
    return FabTokenDriver(FabTokenPublicParams())


def synth(driver, tx, qty, index=0, owner=OWNER, token_type="USD"):
    """One synthetic decoded StoredToken (fabtoken clear-text bytes)."""
    tid = ID(tx, index)
    out = Token(Owner(owner), token_type, hex(qty)).to_bytes()
    return decoded_token(driver.output_to_unspent, tid, out, None)


def mk_vault(store=None, driver=None):
    drv = driver or _driver()
    return Vault(drv, lambda ident: ident == OWNER, store=store), drv


def fill(vault, drv, quantities, tx_prefix="t", token_type="USD"):
    vault.store.apply(VaultDelta("fill", stores=[
        synth(drv, f"{tx_prefix}{i}", q, token_type=token_type)
        for i, q in enumerate(quantities)
    ]))


# ===================================================================
# Store + index units
# ===================================================================


def test_bucket_orders_and_compacts():
    b = _Bucket()
    for i, q in enumerate([5, 50, 1, 30, 7, 42, 9, 3, 11, 2]):
        b.add(f"k{i}", q)
    assert len(b) == 10
    assert [-nq for nq, _k in b.merged()] == sorted(
        [5, 50, 1, 30, 7, 42, 9, 3, 11, 2], reverse=True
    )
    # merged() snapshots are immutable: later adds build a NEW list
    snap = b.merged()
    b.add("k10", 100)
    assert b.merged() is not snap and b.merged()[0] == (-100, "k10")
    # the dead PREFIX trims on every merged() — spent-largest-first is
    # the dominant pattern, so selection never re-walks its own spends
    # even while mid-list tombstones are below the rebuild threshold
    b.discard("k10")  # the current front (qty 100)
    b.discard("k1")   # next front (qty 50)
    trimmed = b.merged()
    assert trimmed[0] == (-42, "k5")  # dead prefix gone
    assert b._stale == 0
    # tombstones compact away once they outnumber the live entries
    for i in range(9):
        b.discard(f"k{i}")
    assert len(b) == 1
    assert b.merged() == [(-2, "k9")]


def test_store_index_and_cert_drop():
    drv = _driver()
    store = InMemoryTokenStore()
    store.apply(VaultDelta("a", stores=[
        synth(drv, "a", 10), synth(drv, "b", 40),
        synth(drv, "c", 25, token_type="EUR"),
    ]))
    store.apply(VaultDelta("", certs=[(ID("b", 0).key(), b"cert-b")]))
    # candidates walk one type only, quantity-descending
    assert [q for q, _k in store.candidates("USD")] == [40, 10]
    assert [q for q, _k in store.candidates("EUR")] == [25]
    assert list(store.candidates("JPY")) == []
    assert store.certification(ID("b", 0).key()) == b"cert-b"
    # spending b drops its token AND its certification (the leak fix)
    before = _counter("vault.certs.dropped")  # counted by the vault layer
    stats = store.apply(VaultDelta("spend", spends=[ID("b", 0).key()]))
    assert stats == {"spent": 1, "stored": 0, "certs_dropped": 1}
    assert store.certification(ID("b", 0).key()) is None
    assert store.cert_count() == 0
    assert _counter("vault.certs.dropped") == before  # vault layer counts it
    # stale index entries filter against the live store
    assert store.get(ID("b", 0).key()) is None
    assert len(store) == 2


def test_vault_api_preserved_and_cert_drop_counted():
    from fabric_token_sdk_tpu.api.request import (
        IssueRecord,
        TokenRequest,
        TransferRecord,
    )
    from fabric_token_sdk_tpu.services.network.ledger import (
        FinalityEvent,
        TxStatus,
    )

    vault, drv = mk_vault()
    outcome = drv.issue(OWNER, "USD", [10, 5], [OWNER, OWNER])
    req = TokenRequest(anchor="issue")
    req.issues.append(IssueRecord(
        action=outcome.action_bytes, issuer=OWNER,
        outputs_metadata=outcome.metadata, receivers=[OWNER, OWNER],
    ))
    vault.on_finality(FinalityEvent("issue", TxStatus.VALID), req)
    assert vault.balance("USD") == 15
    # insertion order preserved (suites zip token_ids with issue values)
    assert [i.key() for i in vault.token_ids()] == ["issue.0", "issue.1"]
    outs, metas = vault.get_many([ID("issue", 0)])
    assert outs[0] == outcome.outputs[0]
    vault.store_certification(ID("issue", 0), b"c0")
    assert vault.certification(ID("issue", 0)) == b"c0"

    # spend issue.0 -> its certification is dropped and counted
    before = _counter("vault.certs.dropped")
    tout = drv.transfer([ID("issue", 0)], [outcome.outputs[0]],
                        [outcome.metadata[0]], "USD", [10], [OWNER])
    treq = TokenRequest(anchor="spend")
    treq.transfers.append(TransferRecord(
        action=tout.action_bytes, input_ids=[ID("issue", 0)],
        senders=[OWNER], outputs_metadata=tout.metadata, receivers=[OWNER],
    ))
    vault.on_finality(FinalityEvent("spend", TxStatus.VALID), treq)
    assert vault.balance("USD") == 15
    assert vault.certification(ID("issue", 0)) is None
    assert _counter("vault.certs.dropped") - before == 1
    # an INVALID event changes nothing
    vault.on_finality(FinalityEvent("spend2", TxStatus.INVALID), treq)
    assert vault.balance("USD") == 15


# ===================================================================
# Persistent store: journal, snapshot, recovery
# ===================================================================


def test_persistent_vault_survives_restart(tmp_path):
    path = str(tmp_path / "vault.wal")
    drv = _driver()
    store = PersistentTokenStore(path, snapshot_every=0)
    vault, _ = mk_vault(store=store, driver=drv)
    fill(vault, drv, [10, 20, 30])
    vault.store_certification(ID("t2", 0), b"cert-30")
    store.apply(VaultDelta("spend", spends=[ID("t0", 0).key()]))
    live_ids = sorted(st.id.key() for st in store.tokens())
    store.close()

    v2 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert sorted(st.id.key() for st in v2.store.tokens()) == live_ids
    assert v2.balance("USD") == 50
    assert v2.certification(ID("t2", 0)) == b"cert-30"
    assert v2.certification(ID("t0", 0)) is None
    # the recovered vault keeps journaling to the same files
    v2.store.apply(VaultDelta("more", stores=[synth(drv, "t9", 9)]))
    v2.store.close()
    v3 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert v3.balance("USD") == 59
    v3.store.close()


def test_vault_recover_truncates_torn_tail(tmp_path):
    path = str(tmp_path / "vault.wal")
    drv = _driver()
    store = PersistentTokenStore(path, snapshot_every=0)
    vault, _ = mk_vault(store=store, driver=drv)
    fill(vault, drv, [7, 8])
    store.close()
    # crash mid-append of the NEXT record: torn header + partial payload
    with open(path, "ab") as fh:
        fh.write(struct.pack(">II", 1 << 20, 0) + b"torn")
    torn_before = _counter("wal.torn_tails")
    v2 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert _counter("wal.torn_tails") - torn_before == 1
    assert v2.balance("USD") == 15  # the acknowledged prefix, exactly
    # the truncated journal accepts fresh appends cleanly
    v2.store.apply(VaultDelta("new", stores=[synth(drv, "n", 1)]))
    v2.store.close()
    v3 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert v3.balance("USD") == 16
    v3.store.close()


def test_vault_snapshot_compaction_and_idempotent_replay(tmp_path):
    path = str(tmp_path / "vault.wal")
    drv = _driver()
    snaps_before = _counter("vault.snapshots")
    store = PersistentTokenStore(path, snapshot_every=4)
    vault, _ = mk_vault(store=store, driver=drv)
    for i in range(6):
        store.apply(VaultDelta(f"e{i}", stores=[synth(drv, f"t{i}", i + 1)]))
    store.apply(VaultDelta("spend", spends=[ID("t0", 0).key()]))
    assert _counter("vault.snapshots") - snaps_before >= 1
    assert os.path.exists(path + ".snap")
    live = sorted(st.id.key() for st in store.tokens())
    balance = vault.balance("USD")

    # normal recovery: snapshot + journal suffix
    store.close()
    v2 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert sorted(st.id.key() for st in v2.store.tokens()) == live
    assert v2.balance("USD") == balance

    # the crash-between-snapshot-and-truncate window: a snapshot that
    # already covers the whole journal, with the journal NOT yet reset —
    # replaying the full journal on top must be idempotent
    with open(path + ".snap", "wb") as fh:
        fh.write(v2.store._snapshot_bytes())
    v2.store.close()  # journal untouched: still holds the suffix records
    v3 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert sorted(st.id.key() for st in v3.store.tokens()) == live
    assert v3.balance("USD") == balance
    v3.store.close()


def test_vault_append_failure_degrades_loudly(tmp_path):
    """An armed `vault.append` fault: the journal append fails, the
    counter + flight event fire, and the IN-MEMORY view still applies —
    durability degrades, correctness of the running process does not."""
    path = str(tmp_path / "vault.wal")
    drv = _driver()
    store = PersistentTokenStore(path, snapshot_every=0)
    vault, _ = mk_vault(store=store, driver=drv)
    fill(vault, drv, [10])
    fails_before = _counter("vault.append_failures")
    injected_before = _counter("faults.injected.vault.append")
    faults.arm("vault.append", "error", count=1)
    store.apply(VaultDelta("lost", stores=[synth(drv, "lost", 5)]))
    assert _counter("vault.append_failures") - fails_before == 1
    assert _counter("faults.injected.vault.append") - injected_before == 1
    assert vault.balance("USD") == 15  # in-memory view intact
    # later events journal again; recovery shows exactly the durable set
    store.apply(VaultDelta("kept", stores=[synth(drv, "kept", 3)]))
    store.close()
    v2 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert v2.get(ID("lost", 0)) is None  # the degraded write is the gap
    assert v2.get(ID("kept", 0)) is not None
    assert v2.balance("USD") == 13
    v2.store.close()


def test_vault_snapshot_and_recover_fault_sites(tmp_path):
    path = str(tmp_path / "vault.wal")
    drv = _driver()
    store = PersistentTokenStore(path, snapshot_every=2)
    vault, _ = mk_vault(store=store, driver=drv)
    # a failing compaction is isolated: counted, journal keeps growing
    snap_fail_before = _counter("vault.snapshot_failures")
    faults.arm("vault.snapshot", "error", count=1)
    fill(vault, drv, [1])
    store.apply(VaultDelta("x", stores=[synth(drv, "x", 2)]))  # boundary
    assert _counter("vault.snapshot_failures") - snap_fail_before == 1
    assert not os.path.exists(path + ".snap")
    assert vault.balance("USD") == 3
    store.close()
    faults.clear()
    # recovery site: an armed error surfaces loudly instead of returning
    # a silently-partial vault
    faults.arm("vault.recover", "error", count=1)
    with pytest.raises(faults.FaultInjected):
        Vault.recover(path, drv, lambda ident: ident == OWNER)
    faults.clear()
    v2 = Vault.recover(path, drv, lambda ident: ident == OWNER)
    assert v2.balance("USD") == 3  # journal alone carries everything
    v2.store.close()


# ===================================================================
# Selector: sharded locks, indexed walk, deadline, self-hold
# ===================================================================


def test_sharded_locker_basics():
    lk = ShardedLocker(shards=4)
    ids = [ID(f"s{i}", 0) for i in range(32)]
    for i in ids:
        assert lk.try_lock(i, "txA")
    assert lk.locked_count() == 32
    assert not lk.try_lock(ids[0], "txB")
    assert lk.holder(ids[0]) == "txA"
    assert lk.is_locked(ids[5])
    lk.unlock(ids[5])
    assert not lk.is_locked(ids[5])
    assert lk.try_lock(ids[5], "txB")
    # unlock_by_tx releases exactly one tx's locks across every shard
    lk.unlock_by_tx("txA")
    assert lk.locked_count() == 1  # txB's lone lock survives
    assert lk.holder(ids[5]) == "txB"
    lk.unlock_by_tx("txB")
    assert lk.locked_count() == 0


def test_selector_walks_candidates_not_vault():
    """Sub-linearity pin (deterministic, no timing): the candidates
    examined per select depend on the amount requested, NOT on how many
    tokens the vault holds."""
    scanned = []
    for n_tokens in (100, 10_000):
        vault, drv = mk_vault()
        vault.store.apply(VaultDelta("fill", stores=[
            synth(drv, f"t{i}", 10) for i in range(n_tokens)
        ]))
        mgr = SelectorManager(vault)
        before = _counter("selector.scanned")
        ids, total = mgr.new_selector("tx").select(30, "USD")
        assert total >= 30 and len(ids) == 3
        scanned.append(_counter("selector.scanned") - before)
        mgr.unlock_by_tx("tx")
    assert scanned[0] == scanned[1] == 3


def test_selector_prefers_largest_and_type_isolation():
    vault, drv = mk_vault()
    fill(vault, drv, [5, 100, 7], tx_prefix="usd")
    fill(vault, drv, [1000], tx_prefix="eur", token_type="EUR")
    mgr = SelectorManager(vault)
    ids, total = mgr.new_selector("tx").select(90, "USD")
    assert [i.tx_id for i in ids] == ["usd1"] and total == 100
    with pytest.raises(InsufficientFunds):
        mgr.new_selector("tx2").select(2000, "EUR")


def test_selector_self_hold_semantics_pinned():
    """Regression pin for the documented re-entrant semantics: tokens a
    tx already earmarked are skipped WITHOUT counting toward a later
    select's total (they can never be spent twice by one tx), so the
    later select asks only for funds beyond the earmarked ones — and
    raises InsufficientFunds when the remainder cannot cover it."""
    vault, drv = mk_vault()
    fill(vault, drv, [100, 10, 10])
    mgr = SelectorManager(vault)
    ids, total = mgr.new_selector("T").select(100, "USD")
    assert total == 100 and len(ids) == 1
    # second select, same tx: the 100-token is self-held -> not counted,
    # not retryable; the two 10s cover a 15
    held_before = _counter("selector.self_held")
    ids2, total2 = mgr.new_selector("T").select(15, "USD")
    assert total2 == 20 and {i.tx_id for i in ids2} == {"t1", "t2"}
    assert _counter("selector.self_held") - held_before >= 1
    # a third select cannot be satisfied by the remainder — typed error,
    # NO retry loop (self-held tokens are not contention)
    retry_before = _counter("selector.retry")
    with pytest.raises(InsufficientFunds):
        mgr.new_selector("T").select(5, "USD")
    assert _counter("selector.retry") == retry_before
    mgr.unlock_by_tx("T")
    assert mgr.locker.locked_count() == 0


def test_selector_deadline_budget():
    """deadline_s switches selection to a WALL-CLOCK budget: however
    many retries fit, the caller gets its typed SelectorTimeout when the
    budget is spent — not after an arbitrary retry count."""
    vault, drv = mk_vault()
    fill(vault, drv, [10])
    mgr = SelectorManager(vault)
    assert mgr.new_selector("holder").select(10, "USD")[1] == 10
    t0 = time.monotonic()
    timeouts_before = _counter("selector.timeout")
    with pytest.raises(SelectorTimeout):
        mgr.new_selector(
            "waiter", retries=10**9, backoff_s=0.01, deadline_s=0.25
        ).select(10, "USD")
    elapsed = time.monotonic() - t0
    assert 0.25 <= elapsed < 5.0
    assert _counter("selector.timeout") - timeouts_before == 1
    # legacy retry-count path still works unchanged
    with pytest.raises(SelectorTimeout):
        mgr.new_selector("w2", retries=2, backoff_s=0.001).select(10, "USD")
    mgr.unlock_by_tx("holder")


def test_selector_stress_no_double_select():
    """Satellite acceptance: K spender threads race over one shared
    token type; no token is ever granted to two txs at once, contention
    counters move, and `unlock_by_tx` releases everything on abort."""
    vault, drv = mk_vault()
    fill(vault, drv, [1] * 60)
    mgr = SelectorManager(vault)
    busy_before = _counter("selector.lock.busy")
    retry_before = _counter("selector.retry")
    in_use = set()
    guard = threading.Lock()
    errors = []
    K, iterations, amount = 6, 8, 15  # 6*15 > 60: guaranteed contention

    def spender(widx):
        try:
            for k in range(iterations):
                tx = f"s{widx}-{k}"
                sel = mgr.new_selector(tx, deadline_s=20.0, backoff_s=0.002)
                ids, total = sel.select(amount, "USD")
                assert total >= amount
                keys = {i.key() for i in ids}
                with guard:
                    clash = in_use & keys
                    assert not clash, f"double-selected {clash}"
                    in_use.update(keys)
                time.sleep(0.001)
                with guard:
                    in_use.difference_update(keys)
                # every path releases via unlock_by_tx (the abort path)
                mgr.unlock_by_tx(tx)
                for i in ids:
                    assert mgr.locker.holder(i) is None
        except Exception as e:  # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=spender, args=(w,)) for w in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads)
    assert not errors, errors[0]
    assert mgr.locker.locked_count() == 0  # nothing leaked
    assert _counter("selector.lock.busy") > busy_before
    assert _counter("selector.retry") > retry_before
    assert vault.balance("USD") == 60  # selection never mutates the vault


def test_selector_lock_fault_site():
    vault, drv = mk_vault()
    fill(vault, drv, [5])
    mgr = SelectorManager(vault)
    injected_before = _counter("faults.injected.selector.lock")
    faults.arm("selector.lock", "delay", delay_s=0.01, count=2)
    ids, total = mgr.new_selector("tx").select(5, "USD")
    assert total == 5
    assert _counter("faults.injected.selector.lock") - injected_before >= 1
    mgr.unlock_by_tx("tx")


# ===================================================================
# ttxdb integrity + scale fixes
# ===================================================================


def test_ttxdb_pk_upsert_index_wal(tmp_path):
    from fabric_token_sdk_tpu.services.ttxdb.db import (
        MovementDirection,
        TransactionDB,
        TxType,
    )

    db = TransactionDB(str(tmp_path / "ttx.db"))
    # crash-consistent concurrent reads on file DBs
    assert db._conn.execute("PRAGMA journal_mode").fetchone()[0] == "wal"
    db.add_transaction("tx1", TxType.TRANSFER, "alice", "bob", "USD", 7)
    db.set_status("tx1", "Confirmed")
    # a resubmission UPSERTS (one row, fresh status) instead of
    # inserting a duplicate that status() would silently shadow
    db.add_transaction("tx1", TxType.TRANSFER, "alice", "bob", "USD", 7)
    assert len(db.transactions()) == 1
    assert db.status("tx1") == "Pending"
    db.set_status("tx1", "Confirmed")
    assert db.status("tx1") == "Confirmed"
    # the movements query path is indexed (wallet_eid, direction, status)
    assert db._conn.execute(
        "SELECT name FROM sqlite_master WHERE type='index' "
        "AND name='mov_wallet_idx'"
    ).fetchone()
    plan = db._conn.execute(
        "EXPLAIN QUERY PLAN SELECT amount FROM movements WHERE "
        "wallet_eid=? AND direction=? AND status='Confirmed'",
        ("alice", "Sent"),
    ).fetchall()
    assert any("mov_wallet_idx" in str(row) for row in plan)
    db.add_movement("tx1", "alice", "USD", 7, MovementDirection.SENT,
                    "Confirmed")
    db.add_movement("tx1", "bob", "USD", 7, MovementDirection.RECEIVED,
                    "Confirmed")
    assert db.payments("alice", "USD") == 7
    assert db.holdings("bob", "USD") == 7
    # in-memory DBs still construct (WAL pragma is a no-op there)
    TransactionDB().add_transaction(
        "m", TxType.ISSUE, "i", "", "USD", 1
    )


def test_ttxdb_migrates_legacy_schema(tmp_path):
    """A DB file created BEFORE tx_id became the PRIMARY KEY (plain
    table + tx_idx index, possibly holding duplicate rows) must reopen
    cleanly: the table is rebuilt with the PK keeping the FIRST row per
    tx_id (the old status() read order), and upserts work from then on."""
    import sqlite3

    from fabric_token_sdk_tpu.services.ttxdb.db import TransactionDB, TxType

    path = str(tmp_path / "legacy.db")
    conn = sqlite3.connect(path)
    conn.executescript(
        """
        CREATE TABLE transactions (
            tx_id TEXT, tx_type TEXT, sender_eid TEXT,
            recipient_eid TEXT, token_type TEXT, amount TEXT,
            status TEXT, timestamp REAL
        );
        CREATE TABLE movements (
            tx_id TEXT, wallet_eid TEXT, token_type TEXT,
            amount TEXT, direction TEXT, status TEXT
        );
        CREATE INDEX tx_idx ON transactions(tx_id);
        INSERT INTO transactions VALUES
            ('dup', 'Transfer', 'a', 'b', 'USD', '5', 'Confirmed', 1.0),
            ('dup', 'Transfer', 'a', 'b', 'USD', '5', 'Pending', 2.0),
            ('solo', 'Issue', 'i', '', 'USD', '9', 'Confirmed', 3.0);
        """
    )
    conn.commit()
    conn.close()
    db = TransactionDB(path)
    # the duplicate collapsed to the FIRST row (old read semantics)
    assert db.status("dup") == "Confirmed"
    assert db.status("solo") == "Confirmed"
    assert len(db.transactions()) == 2
    # and the upsert path now works on the migrated file
    db.add_transaction("dup", TxType.TRANSFER, "a", "b", "USD", 5)
    assert db.status("dup") == "Pending"
    assert len(db.transactions()) == 2


def test_party_persistent_vault_end_to_end(tmp_path):
    """Product-path integration: a Party built with `vault_path=` runs a
    real issue+transfer flow over the network, is torn down, and a
    REBUILT party on the same path recovers its owned tokens — the
    client restart no longer loses every owned token."""
    from fabric_token_sdk_tpu.api.validator import RequestValidator
    from fabric_token_sdk_tpu.api.wallet import AuditorWallet
    from fabric_token_sdk_tpu.crypto import sign
    from fabric_token_sdk_tpu.services.auditor import AuditorService
    from fabric_token_sdk_tpu.services.network import Network
    from fabric_token_sdk_tpu.services.ttx import Party, Transaction

    def mk():
        return FabTokenDriver(FabTokenPublicParams())

    aw = AuditorWallet("auditor", sign.keygen())
    auditor_svc = AuditorService(mk(), aw)
    network = Network(RequestValidator(mk(), aw.identity))
    network.subscribe(auditor_svc.on_finality)
    vault_path = str(tmp_path / "alice-vault.wal")
    issuer_p = Party("issuer-node", mk(), network, auditor_identity=aw.identity)
    alice_p = Party("alice-node", mk(), network, auditor_identity=aw.identity,
                    vault_path=vault_path)
    issuer = issuer_p.new_issuer_wallet("issuer")
    alice = alice_p.new_owner_wallet("alice", anonymous=False)

    tx = Transaction(issuer_p, "tx-issue")
    tx.issue("issuer", "USD", [10, 5],
             [alice.recipient_identity(), alice.recipient_identity()],
             anonymous=False)
    tx.collect_endorsements(auditor_svc)
    tx.submit()
    assert alice_p.balance("USD") == 15
    alice_p.vault.store.close()

    # "restart": a new party over the same journal path; the wallet key
    # material is re-registered (identity layer), the TOKENS come back
    # from the vault journal
    alice2 = Party("alice-node", mk(), network, auditor_identity=aw.identity,
                   vault_path=vault_path)
    assert alice2.balance("USD") == 15
    assert sorted(i.key() for i in alice2.vault.token_ids()) == [
        "tx-issue.0", "tx-issue.1"
    ]
    alice2.vault.store.close()


# ===================================================================
# Bench state_scale phase (reduced config) + schema
# ===================================================================


def test_state_scale_phase_reduced(monkeypatch):
    """End-to-end run of the bench `state_scale` phase at a reduced size:
    populate -> compact -> recover -> concurrent select+spend, emitting a
    section that validates against the shared bench schema — and proving
    the sub-linearity witness is recorded."""
    import bench
    from fabric_token_sdk_tpu.utils import benchschema

    for key, val in (("FTS_BENCH_STATE_TOKENS", "3000"),
                     ("FTS_BENCH_STATE_SMALL", "600"),
                     ("FTS_BENCH_STATE_THREADS", "2"),
                     ("FTS_BENCH_STATE_SELECTS", "30"),
                     ("FTS_BENCH_STATE_BATCH", "1000"),
                     ("FTS_BENCH_STATE_S", "20")):
        monkeypatch.setenv(key, val)
    hb = types.SimpleNamespace(set_phase=lambda *a, **k: None)
    state = bench._state_scale(hb)
    assert benchschema.validate_state(state) == []
    assert state["tokens"] == 3000
    assert state["selects"] > 0 and state["spends"] > 0
    assert state["recover_tokens_per_s"] > 0
    assert state["rss_high_water_mb"] > 0
    assert state["sublinear_ratio"] is not None


# ===================================================================
# Chaos acceptance: SIGKILL a client mid-spend-workload
# ===================================================================

_CLIENT_CHILD = """
import os, sys
sys.path.insert(0, sys.argv[2])
from fabric_token_sdk_tpu.api.request import IssueRecord, TokenRequest, TransferRecord
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.network.ledger import FinalityEvent, TxStatus
from fabric_token_sdk_tpu.services.vault import PersistentTokenStore, Vault

path = sys.argv[1]
me = b"chaos-owner"
drv = FabTokenDriver(FabTokenPublicParams())
store = PersistentTokenStore(path, snapshot_every=8)
vault = Vault(drv, lambda ident: ident == me, store=store)

outcome = drv.issue(me, "USD", [5] * 8, [me] * 8)
req = TokenRequest(anchor="seed")
req.issues.append(IssueRecord(action=outcome.action_bytes, issuer=me,
                              outputs_metadata=outcome.metadata,
                              receivers=[me] * 8))
vault.on_finality(FinalityEvent("seed", TxStatus.VALID), req)
vault.store_certification(ID("seed", 0), b"cert-seed-0")
print("ACK seed", flush=True)

prev, prev_raw, prev_meta = ID("seed", 0), outcome.outputs[0], outcome.metadata[0]
k = 0
while True:
    tx = f"spend-{k}"
    tout = drv.transfer([prev], [prev_raw], [prev_meta], "USD", [5], [me])
    treq = TokenRequest(anchor=tx)
    treq.transfers.append(TransferRecord(
        action=tout.action_bytes, input_ids=[prev], senders=[me],
        outputs_metadata=tout.metadata, receivers=[me]))
    vault.on_finality(FinalityEvent(tx, TxStatus.VALID), treq)
    print(f"ACK {tx}", flush=True)
    prev, prev_raw, prev_meta = ID(tx, 0), tout.outputs[0], tout.metadata[0]
    k += 1
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_client_vault_recovers(tmp_path):
    """Acceptance: a client process SIGKILLed mid-spend-workload (with
    `FTS_FAULTS` delay injection armed on `vault.append` to widen the
    kill window) recovers via `Vault.recover` with balances exactly
    equal to the acknowledged-finality replay — every acknowledged spend
    is applied (no double-spendable phantom of a spent token), the
    spent token's certification is gone, an artificially torn journal
    tail is truncated cleanly, and a fresh selector can lock every
    recovered token (zero leaked locks)."""
    path = str(tmp_path / "client-vault.wal")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FTS_FAULTS="vault.append:delay:1.0:1000000:0.005")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CLIENT_CHILD, path, REPO_ROOT],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
    )
    acked = []
    deadline = time.time() + 120
    try:
        while time.time() < deadline and len(acked) < 10:
            if proc.poll() is not None:
                raise AssertionError(
                    f"chaos child died rc={proc.returncode}:\n"
                    f"{proc.stderr.read()}"
                )
            ready, _, _ = select.select([proc.stdout], [], [], 0.2)
            if ready:
                line = proc.stdout.readline()
                assert line.startswith("ACK"), line
                acked.append(line.split()[1])
        assert len(acked) >= 10, f"child too slow, acked only {acked}"
        os.kill(proc.pid, signal.SIGKILL)  # mid-workload, no warning
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()

    # crash-simulate a torn final record on top of whatever the kill
    # left (the journal may legitimately be freshly compacted-empty —
    # snapshot_every=8 fired mid-workload — but the snapshot must exist)
    assert os.path.getsize(path) > 0 or os.path.exists(path + ".snap")
    with open(path, "ab") as fh:
        fh.write(struct.pack(">II", 1 << 20, 0) + b"torn")

    drv = _driver()
    torn_before = _counter("wal.torn_tails")
    vault = Vault.recover(path, drv, lambda ident: ident == b"chaos-owner")
    assert _counter("wal.torn_tails") - torn_before == 1

    # conservation: every event preserves 8 tokens x 5 USD
    assert vault.balance("USD") == 40
    held = {st.id.key() for st in vault.store.tokens()}
    # the recovered state is the replay of a PREFIX at least as long as
    # the acknowledged one: seed.1..seed.7 plus exactly one chain head
    # spend-M.0 with M >= the last acknowledged spend (the kill can land
    # after a journal append but before its ACK printed)
    spends_acked = [a for a in acked if a.startswith("spend-")]
    last_acked = max(int(a.split("-")[1]) for a in spends_acked)
    base = {f"seed.{i}" for i in range(1, 8)}
    assert base <= held
    heads = held - base
    assert len(heads) == 1, f"unexpected recovered set: {held}"
    head = heads.pop()
    assert head.startswith("spend-")
    m = int(head.split("-")[1].split(".")[0])
    assert m >= last_acked
    # no double-spendable phantoms: every acknowledged-spent token is gone
    assert "seed.0" not in held
    for k in range(m):
        assert f"spend-{k}.0" not in held
    # the spent seed token's certification died with it
    assert vault.certification(ID("seed", 0)) is None

    # zero leaked selector locks: a fresh selector can lock EVERY token
    mgr = SelectorManager(vault)
    ids, total = mgr.new_selector("post-recovery").select(40, "USD")
    assert total == 40 and len(ids) == 8
    mgr.unlock_by_tx("post-recovery")
    assert mgr.locker.locked_count() == 0
    # and the recovered vault accepts + journals fresh work
    vault.store.apply(VaultDelta("fresh", stores=[synth(drv, "fresh", 2,
                                                        owner=b"chaos-owner")]))
    vault.store.close()
    v2 = Vault.recover(path, drv, lambda ident: ident == b"chaos-owner")
    assert v2.balance("USD") == 42
    v2.store.close()
