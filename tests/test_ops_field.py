"""Differential tests: TPU limb/field kernels vs host big-int math."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.ops import FP, FR, limbs as lb


def test_limb_roundtrip(rng):
    xs = [rng.randrange(1 << 256) for _ in range(8)]
    arr = lb.ints_to_limbs(xs)
    assert lb.batch_limbs_to_ints(arr) == xs


def test_mul_full_matches_host(rng):
    xs = [rng.randrange(1 << 256) for _ in range(4)]
    ys = [rng.randrange(1 << 256) for _ in range(4)]
    prod = lb.mul_full(jnp.asarray(lb.ints_to_limbs(xs)), jnp.asarray(lb.ints_to_limbs(ys)))
    got = lb.batch_limbs_to_ints(np.asarray(prod))
    assert got == [x * y for x, y in zip(xs, ys)]


def test_mul_low_matches_host(rng):
    xs = [rng.randrange(1 << 256) for _ in range(4)]
    ys = [rng.randrange(1 << 256) for _ in range(4)]
    prod = lb.mul_low(jnp.asarray(lb.ints_to_limbs(xs)), jnp.asarray(lb.ints_to_limbs(ys)))
    got = lb.batch_limbs_to_ints(np.asarray(prod))
    assert got == [(x * y) % (1 << 256) for x, y in zip(xs, ys)]


def test_compare_ge(rng):
    pairs = [(5, 5), (4, 9), (9, 4), (1 << 255, (1 << 255) - 1)]
    x = jnp.asarray(lb.ints_to_limbs([a for a, _ in pairs]))
    y = jnp.asarray(lb.ints_to_limbs([b for _, b in pairs]))
    got = np.asarray(lb.compare_ge(x, y))
    assert list(got) == [a >= b for a, b in pairs]


@pytest.mark.parametrize("F,mod", [(FP, hm.P), (FR, hm.R)])
def test_field_mul_add_sub(F, mod, rng):
    xs = [rng.randrange(mod) for _ in range(6)]
    ys = [rng.randrange(mod) for _ in range(6)]
    X, Y = F.encode(xs), F.encode(ys)
    assert F.decode(F.mul(X, Y)) == [(a * b) % mod for a, b in zip(xs, ys)]
    assert F.decode(F.add(X, Y)) == [(a + b) % mod for a, b in zip(xs, ys)]
    assert F.decode(F.sub(X, Y)) == [(a - b) % mod for a, b in zip(xs, ys)]
    assert F.decode(F.neg(X)) == [(-a) % mod for a in xs]


def test_field_edge_values():
    mod = FP.modulus
    xs = [0, 1, mod - 1, mod - 2]
    X = FP.encode(xs)
    assert FP.decode(FP.add(X, X)) == [(2 * a) % mod for a in xs]
    assert FP.decode(FP.sub(X, FP.encode([1, 1, 1, 1]))) == [(a - 1) % mod for a in xs]
    assert FP.decode(FP.mul(X, X)) == [(a * a) % mod for a in xs]


def test_field_inv_pow(rng):
    mod = FP.modulus
    xs = [rng.randrange(1, mod) for _ in range(4)]
    X = FP.encode(xs)
    inv = FP.inv(X)
    assert FP.decode(FP.mul(X, inv)) == [1] * 4
    e = 0xDEADBEEF
    assert FP.decode(FP.pow_const(X, e)) == [pow(a, e, mod) for a in xs]


def test_field_under_jit(rng):
    mod = FR.modulus
    xs = [rng.randrange(mod) for _ in range(3)]
    X = FR.encode(xs)

    @jax.jit
    def f(a):
        return FR.mul(FR.add(a, a), a)

    assert FR.decode(f(X)) == [(2 * a * a) % mod for a in xs]
