"""Batch-first host validation: differential identity with the scalar path.

The host batch passes (`FTS_HOST_BATCH`) — block-level Fiat-Shamir
(`hostmath.hash_to_zr_many`), batched Schnorr verification
(`sign.verify_many`), batched WF/transfer-proof verification
(`wellformedness.verify_transfer_wfs` / `transfer.verify_transfer_proofs`),
vectorized conservation (`Driver.validate_conservation_many`), the parsed
request/token caches, and the `host_map` commit-worker fan-out — can only
ACCELERATE host validation, never change accept/reject or an error
message. These tests pin that contract: challenge byte-identity with the
scalar hash (native sha256 present and absent), per-row verdict identity
over valid/tampered rows (native bn254 present and absent), end-to-end
block differentials (valid + tampered + double-spend corpora, both
drivers, batch on vs `FTS_HOST_BATCH=0`, workers 1 vs N), cache hit/miss
accounting + clone isolation + bounded eviction, and the `ops.health`
caches section.
"""
import random
import threading

import pytest

import fabric_token_sdk_tpu.native as native
from fabric_token_sdk_tpu.api import request as request_mod
from fabric_token_sdk_tpu.api.request import (
    IssueRecord,
    TokenRequest,
    TransferRecord,
)
from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.crypto import hostmath as hm
from fabric_token_sdk_tpu.crypto import sign
from fabric_token_sdk_tpu.crypto.serialization import dumps, loads
from fabric_token_sdk_tpu.crypto.setup import setup
from fabric_token_sdk_tpu.drivers import identity
from fabric_token_sdk_tpu.drivers.fabtoken import (
    FabTokenDriver,
    FabTokenPublicParams,
)
from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver
from fabric_token_sdk_tpu.models.token import ID
from fabric_token_sdk_tpu.services.network import (
    BlockPolicy,
    Network,
    TxStatus,
)
from fabric_token_sdk_tpu.services.network import pipeline as npipe
from fabric_token_sdk_tpu.utils import metrics as mx


def _counter(name):
    return mx.REGISTRY.counter(name).value


@pytest.fixture(scope="module")
def zk_pp():
    return setup(base=4, exponent=2, rng=random.Random(0xF75))


@pytest.fixture(autouse=True)
def _fresh_request_cache():
    request_mod.cache_clear()
    yield
    request_mod.cache_clear()


def _no_native_sha(monkeypatch):
    """Simulate the native fastser library being absent: `sha256_many`
    falls back to scalar hashlib inside."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)


# ===================================================================
# Block-level Fiat-Shamir: byte-identical challenges
# ===================================================================


def test_hash_to_zr_many_matches_scalar_native_on_and_off(monkeypatch):
    items = [
        (bytes([i % 251]) * (i * 7 % 40), b"fts/dom-%d" % (i % 3))
        for i in range(25)
    ] + [(b"", b"fts/empty")]
    want = [hm.hash_to_zr(d, dom) for d, dom in items]
    assert hm.hash_to_zr_many(items) == want
    assert hm.hash_to_zr_many([]) == []
    assert hm.hash_to_zr_many(iter(items)) == want  # any iterable
    _no_native_sha(monkeypatch)
    assert hm.hash_to_zr_many(items) == want


# ===================================================================
# Batched host Schnorr verify: per-row verdict identity
# ===================================================================


def _host_ok(pk, msg, sig_raw):
    try:
        pk.verify(msg, sig_raw)
        return True
    except ValueError:
        return False


def _sig_rows(rng):
    """Every row class with its expected batch verdict (None = the
    scalar path owns the decision)."""
    keys = [sign.keygen(rng) for _ in range(3)]
    rows, expect = [], []

    def add(pk, msg, sig_raw, want="host"):
        rows.append((pk.point, msg, sig_raw))
        expect.append(
            _host_ok(pk, msg, sig_raw) if want == "host" else want
        )

    for i in range(5):  # valid rows, repeated signers
        k = keys[i % 3]
        msg = b"pay-%d" % i
        add(k.public, msg, k.sign(msg, rng))
    k = keys[0]
    good = k.sign(b"tamper-me", rng)
    d = loads(good)
    d["c"] ^= 1
    add(k.public, b"tamper-me", dumps(d))  # bit-flipped challenge
    d = loads(good)
    d["z"] ^= 1
    add(k.public, b"tamper-me", dumps(d))  # bit-flipped response
    add(k.public, b"tamper-ME", good)  # flipped message
    add(keys[1].public, b"tamper-me", good)  # wrong pk
    add(k.public, b"x", b"\x00not-a-sig", want=None)  # unparseable
    d = loads(good)
    d["c"] = "not-an-int"
    add(k.public, b"x", dumps(d), want=None)  # non-integer field
    return rows, expect


def test_verify_many_differential_native_on_and_off(rng, monkeypatch):
    rows, expect = _sig_rows(rng)
    assert sign.verify_many(rows) == expect
    assert sign.verify_many([]) == []
    # pure-python bn254 fallback + scalar sha fallback: same verdicts
    n0 = _counter("hostmath.g1_multiexp_rows.python")
    monkeypatch.setattr(hm, "NATIVE_G1", False)
    _no_native_sha(monkeypatch)
    assert sign.verify_many(rows) == expect
    assert _counter("hostmath.g1_multiexp_rows.python") > n0


# ===================================================================
# Batched host proof verify (zkatdlog 1-in/1-out shape)
# ===================================================================


def _zk_rows(zk_pp, rng):
    """Three plan rows: valid 1-in/1-out, proof-tampered 1-in/1-out,
    and a range-carrying 1-in/2-out shape the batch must leave alone."""
    drv = ZKATDLogDriver(zk_pp)
    out = drv.issue(b"issuer", "USD", [3], [b"alice"], rng=rng)
    t = drv.transfer(
        [ID("seed", 0)], [out.outputs[0]], [out.metadata[0]],
        "USD", [3], [b"alice"], rng=rng,
    )
    shape, good_row = drv.transfer_batch_plan(t.action_bytes)
    assert shape == (1, 1)
    d = loads(t.action_bytes)
    p = bytearray(d["proof"])
    p[len(p) // 2] ^= 1
    d["proof"] = bytes(p)
    _shape, bad_row = drv.transfer_batch_plan(dumps(d))
    out2 = drv.issue(b"issuer", "USD", [4], [b"alice"], rng=rng)
    t2 = drv.transfer(
        [ID("seed2", 0)], [out2.outputs[0]], [out2.metadata[0]],
        "USD", [1, 3], [b"alice", b"alice"], rng=rng,
    )
    shape2, range_row = drv.transfer_batch_plan(t2.action_bytes)
    assert shape2 == (1, 2)
    return drv, good_row, bad_row, range_row


def test_transfer_host_batch_differential(zk_pp, rng, monkeypatch):
    drv, good_row, bad_row, range_row = _zk_rows(zk_pp, rng)
    oks = drv.transfer_host_batch([good_row, bad_row, range_row])
    assert oks[0] is True  # the WF challenge compare IS the decision
    assert oks[1] is not True  # tampered: scalar path owns the error
    assert oks[2] is None  # range shape: never batch-decidable
    # same verdicts without any native library
    monkeypatch.setattr(hm, "NATIVE_G1", False)
    _no_native_sha(monkeypatch)
    oks2 = drv.transfer_host_batch([good_row, bad_row, range_row])
    assert oks2[0] is True and oks2[1] is not True and oks2[2] is None


# ===================================================================
# Vectorized conservation (fabtoken)
# ===================================================================


def test_validate_conservation_many_differential():
    pp = FabTokenPublicParams()
    drv = FabTokenDriver(pp)
    key = sign.keygen(random.Random(3))
    ident = identity.pk_identity(key.public)
    tok5 = drv.issue(ident, "USD", [5], [ident]).outputs[0]
    tok4 = drv.issue(ident, "USD", [4], [ident]).outputs[0]
    tok_eur = drv.issue(ident, "EUR", [5], [ident]).outputs[0]
    ok = dumps({"inputs": [tok5], "outputs": [tok5]})
    ok_split = dumps({"inputs": [tok5, tok4], "outputs": [tok4, tok5]})
    bad_sum = dumps({"inputs": [tok5], "outputs": [tok4]})
    bad_type = dumps({"inputs": [tok5], "outputs": [tok_eur]})
    malformed_tok = dumps({"inputs": [b"\x00junk"], "outputs": [tok5]})
    empty = dumps({"inputs": [], "outputs": [tok5]})
    oks = drv.validate_conservation_many(
        [ok, ok_split, bad_sum, bad_type, malformed_tok, empty, b"\x00"]
    )
    # True only where the scalar conservation leg would accept; anything
    # the column pass cannot prove stays None for the scalar re-check
    assert oks == [True, True, None, None, None, None, None]
    assert drv.validate_conservation_many([]) == []


# ===================================================================
# host_map: the commit-worker fan-out
# ===================================================================


def test_host_map_order_and_inline_routing(monkeypatch):
    items = list(range(100))

    def double(chunk):
        return [x * 2 for x in chunk]

    monkeypatch.setenv("FTS_COMMIT_WORKERS", "3")
    assert npipe.host_workers() == 3
    assert npipe.host_map(double, items) == [x * 2 for x in items]
    # small batches run inline (no pool), same result
    assert npipe.host_map(double, items[:5]) == [x * 2 for x in items[:5]]
    # workers=1 is the inline kill switch
    monkeypatch.setenv("FTS_COMMIT_WORKERS", "1")
    assert npipe.host_workers() == 1
    assert npipe.host_map(double, items) == [x * 2 for x in items]
    monkeypatch.setenv("FTS_COMMIT_WORKERS", "junk")
    assert npipe.host_workers() >= 1  # junk -> auto


def test_host_map_worker_exception_propagates(monkeypatch):
    monkeypatch.setenv("FTS_COMMIT_WORKERS", "2")

    def boom(chunk):
        raise RuntimeError("worker died")

    with pytest.raises(RuntimeError, match="worker died"):
        npipe.host_map(boom, list(range(64)))


# ===================================================================
# End-to-end block differential (fabtoken: sign + conservation passes)
# ===================================================================


def _fab_corpus(n_transfers=6, tamper=None):
    """1 issue seed + a chain of pk-signed self-transfers; `tamper`
    injects a bit-flipped owner signature at t2 and/or appends a
    double spend of t0's output (already consumed by t1)."""
    pp = FabTokenPublicParams()
    drv = FabTokenDriver(pp)
    key = sign.keygen(random.Random(7))
    ident = identity.pk_identity(key.public)
    reqs = []
    out = drv.issue(ident, "USD", [9], [ident])
    req = TokenRequest(anchor="seed")
    req.issues.append(
        IssueRecord(action=out.action_bytes, issuer=ident,
                    outputs_metadata=out.metadata, receivers=[ident])
    )
    req.issues[0].signature = key.sign(
        req.marshal_to_sign(), random.Random(11)
    )
    reqs.append(req.to_bytes())
    prev, prev_raw = ID("seed", 0), out.outputs[0]
    outputs = {}
    for k in range(n_transfers):
        t = drv.transfer([prev], [prev_raw], [prev_raw], "USD", [9], [ident])
        tr = TokenRequest(anchor=f"t{k}")
        tr.transfers.append(
            TransferRecord(action=t.action_bytes, input_ids=[prev],
                           senders=[ident], outputs_metadata=t.metadata,
                           receivers=[ident])
        )
        sig = key.sign(tr.marshal_to_sign(), random.Random(100 + k))
        if k == 2 and tamper in ("sig", "all"):
            d = loads(sig)
            d["z"] ^= 1
            sig = dumps(d)
        tr.transfers[0].signatures = [sig]
        reqs.append(tr.to_bytes())
        outputs[k] = (prev, prev_raw)
        prev, prev_raw = ID(f"t{k}", 0), t.outputs[0]
    if tamper in ("double_spend", "all"):
        spent_id, spent_raw = ID("t0", 0), outputs.get(1, (None, None))[1]
        t = drv.transfer(
            [spent_id], [spent_raw], [spent_raw], "USD", [9], [ident]
        )
        tr = TokenRequest(anchor="dsp")
        tr.transfers.append(
            TransferRecord(action=t.action_bytes, input_ids=[spent_id],
                           senders=[ident], outputs_metadata=t.metadata,
                           receivers=[ident])
        )
        tr.transfers[0].signatures = [
            key.sign(tr.marshal_to_sign(), random.Random(999))
        ]
        reqs.append(tr.to_bytes())
    return pp, reqs


def _outcomes(events):
    return [(e.tx_id, e.status, e.message) for e in events]


def _fab_run(pp, reqs):
    net = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(max_block_txs=32),
    )
    return _outcomes(net.submit_many(reqs))


def test_fabtoken_block_differential_batch_on_off_and_workers(monkeypatch):
    """Statuses AND error messages are byte-identical across: host batch
    on (default), N commit workers, native math absent, and the
    scalar baseline (`FTS_HOST_BATCH=0`)."""
    pp, reqs = _fab_corpus(tamper="all")
    monkeypatch.setenv("FTS_HOST_BATCH", "0")
    baseline = _fab_run(pp, reqs)
    by_id = {tx: st for tx, st, _ in baseline}
    assert by_id["seed"] == TxStatus.VALID
    assert by_id["t1"] == TxStatus.VALID
    assert by_id["t2"] == TxStatus.INVALID  # tampered signature
    assert by_id["t3"] == TxStatus.INVALID  # chain broken by t2
    assert by_id["dsp"] == TxStatus.INVALID  # double spend
    assert any("already spent" in m for _t, _s, m in baseline)

    s0, p0 = _counter("hostbatch.sign.rows"), _counter(
        "hostbatch.conservation.rows"
    )
    monkeypatch.setenv("FTS_HOST_BATCH", "1")
    request_mod.cache_clear()
    assert _fab_run(pp, reqs) == baseline
    # the host batch passes actually ran (CPU auto-mode keeps the sign
    # plane host-side, so the block sign batch owns the valid rows)
    assert _counter("hostbatch.sign.rows") > s0
    assert _counter("hostbatch.conservation.rows") > p0

    monkeypatch.setenv("FTS_COMMIT_WORKERS", "4")
    request_mod.cache_clear()
    assert _fab_run(pp, reqs) == baseline

    monkeypatch.setattr(hm, "NATIVE_G1", False)
    _no_native_sha(monkeypatch)
    request_mod.cache_clear()
    assert _fab_run(pp, reqs) == baseline


# ===================================================================
# End-to-end block differential (zkatdlog: host proof batch leftovers)
# ===================================================================


def _zk_corpus(zk_pp, rng):
    """Chained 1-in/1-out zk transfers + a proof-tampered tx (re-signed
    so the PROOF check, not the signature, decides) + a double spend."""
    drv = ZKATDLogDriver(zk_pp)
    key = sign.keygen(random.Random(21))
    ident = identity.pk_identity(key.public)
    reqs = []
    out = drv.issue(ident, "USD", [3], [ident], rng=rng)
    req = TokenRequest(anchor="seed")
    req.issues.append(
        IssueRecord(action=out.action_bytes, issuer=ident,
                    outputs_metadata=out.metadata, receivers=[ident])
    )
    req.issues[0].signature = key.sign(
        req.marshal_to_sign(), random.Random(31)
    )
    reqs.append(req.to_bytes())
    prev, prev_tok, prev_meta = ID("seed", 0), out.outputs[0], out.metadata[0]
    for k in range(4):
        t = drv.transfer(
            [prev], [prev_tok], [prev_meta], "USD", [3], [ident], rng=rng
        )
        action = t.action_bytes
        if k == 2:  # tamper the zk proof, then sign the TAMPERED action
            d = loads(action)
            p = bytearray(d["proof"])
            p[len(p) // 2] ^= 1
            d["proof"] = bytes(p)
            action = dumps(d)
        tr = TokenRequest(anchor=f"z{k}")
        tr.transfers.append(
            TransferRecord(action=action, input_ids=[prev],
                           senders=[ident], outputs_metadata=t.metadata,
                           receivers=[ident])
        )
        tr.transfers[0].signatures = [
            key.sign(tr.marshal_to_sign(), random.Random(200 + k))
        ]
        reqs.append(tr.to_bytes())
        if k == 0:
            spent = (prev, prev_tok, prev_meta)
        prev, prev_tok, prev_meta = ID(f"z{k}", 0), t.outputs[0], t.metadata[0]
    # double spend: re-spend the seed output z0 already consumed
    sid, stok, smeta = spent
    t = drv.transfer([sid], [stok], [smeta], "USD", [3], [ident], rng=rng)
    tr = TokenRequest(anchor="zdsp")
    tr.transfers.append(
        TransferRecord(action=t.action_bytes, input_ids=[sid],
                       senders=[ident], outputs_metadata=t.metadata,
                       receivers=[ident])
    )
    tr.transfers[0].signatures = [
        key.sign(tr.marshal_to_sign(), random.Random(998))
    ]
    reqs.append(tr.to_bytes())
    return reqs


def _zk_run(zk_pp, reqs):
    # min_batch above the block size: every plannable row is a device
    # leftover, i.e. exactly the host proof batch's input
    net = Network(
        RequestValidator(ZKATDLogDriver(zk_pp)),
        policy=BlockPolicy(max_block_txs=32, min_batch=99, use_batched=True),
    )
    return _outcomes(net.submit_many(reqs))


def test_zkatdlog_block_differential_host_proof_batch(zk_pp, rng, monkeypatch):
    reqs = _zk_corpus(zk_pp, rng)
    monkeypatch.setenv("FTS_HOST_BATCH", "0")
    r0 = _counter("hostbatch.proof.rows")
    baseline = _zk_run(zk_pp, reqs)
    assert _counter("hostbatch.proof.rows") == r0  # kill switch honored
    by_id = {tx: st for tx, st, _ in baseline}
    assert by_id["seed"] == TxStatus.VALID
    assert by_id["z0"] == TxStatus.VALID
    assert by_id["z1"] == TxStatus.VALID
    assert by_id["z2"] == TxStatus.INVALID  # tampered proof
    assert by_id["z3"] == TxStatus.INVALID  # chain broken by z2
    assert by_id["zdsp"] == TxStatus.INVALID  # double spend

    monkeypatch.setenv("FTS_HOST_BATCH", "1")
    request_mod.cache_clear()
    assert _zk_run(zk_pp, reqs) == baseline
    # the valid leftover rows were proved by the batch pass
    assert _counter("hostbatch.proof.rows") > r0
    flights = [
        e for e in mx.FLIGHT.tail() if e["kind"] == "verify.host_batch"
    ]
    assert flights and flights[-1]["verified"] >= 1


# ===================================================================
# Parsed-request cache
# ===================================================================


def test_request_cache_hits_misses_and_clone_isolation():
    pp, reqs = _fab_corpus(n_transfers=2)
    raw = reqs[1]
    h0, m0 = _counter("request.cache.hits"), _counter("request.cache.misses")
    r1 = TokenRequest.from_bytes(raw)
    assert _counter("request.cache.misses") == m0 + 1
    r2 = TokenRequest.from_bytes(raw)
    assert _counter("request.cache.hits") == h0 + 1
    assert r2.to_bytes() == raw
    assert r2.wire_bytes() == raw  # unmutated: the exact wire bytes
    # clone isolation: mutating one parse never corrupts later lookups
    r2.transfers[0].signatures[0] = b"corrupted"
    r2.anchor = "mutated"
    assert r2.wire_bytes() != raw  # reassignment drops the wire memo
    r3 = TokenRequest.from_bytes(raw)
    assert r3.to_bytes() == raw
    assert r3.anchor == r1.anchor
    assert request_mod.cache_len() >= 1
    request_mod.cache_clear()
    assert request_mod.cache_len() == 0


def test_request_cache_bounded_eviction_and_flight(monkeypatch):
    monkeypatch.setenv("FTS_REQUEST_CACHE", "4")
    request_mod.cache_clear()  # re-resolve capacity from env
    e0 = _counter("request.cache.evictions")
    raws = []
    for i in range(10):
        r = TokenRequest(anchor=f"evict-{i}")
        raws.append(r.to_bytes())
    for raw in raws:
        TokenRequest.from_bytes(raw)
    assert request_mod.cache_len() == 4  # bounded
    assert _counter("request.cache.evictions") - e0 == 6
    evt = [
        e for e in mx.FLIGHT.tail() if e["kind"] == "request.cache.evict"
    ][-1]
    assert evt["capacity"] == 4 and evt["size"] <= 4
    # capacity 0 disables storage AND counters
    monkeypatch.setenv("FTS_REQUEST_CACHE", "0")
    request_mod.cache_clear()
    h0, m0 = _counter("request.cache.hits"), _counter("request.cache.misses")
    TokenRequest.from_bytes(raws[0])
    TokenRequest.from_bytes(raws[0])
    assert request_mod.cache_len() == 0
    assert _counter("request.cache.hits") == h0
    assert _counter("request.cache.misses") == m0


# ===================================================================
# ops.health caches section
# ===================================================================


def test_health_reports_cache_section():
    pp, reqs = _fab_corpus(n_transfers=2)
    net = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(max_block_txs=8),
    )
    net.submit_many(reqs)
    caches = net.health()["caches"]
    assert set(caches) == {"identity", "request", "parse"}
    for section in caches.values():
        assert section["hits"] >= 0 and section["misses"] >= 0
    assert caches["request"]["entries"] == request_mod.cache_len()
    assert "evictions" in caches["request"]
