"""Live ops plane: latency quantiles, memory telemetry, node
introspection RPCs served DURING commits, typed shutdown answers, and
the `ftstop` live view + perf-regression observatory.

Acceptance: a real `LedgerServer` under a driven workload answers
`ops.health` / `ops.metrics` mid-run — queue depth, height and a
nonzero block-commit p95 come back live, and probes never block behind
a slow commit; `ftstop compare` flags an injected regression between
two synthetic bench records.
"""

import json
import os
import sys
import threading
import time

import pytest

from fabric_token_sdk_tpu.api.validator import RequestValidator
from fabric_token_sdk_tpu.api.request import TokenRequest
from fabric_token_sdk_tpu.drivers.fabtoken import FabTokenDriver, FabTokenPublicParams
from fabric_token_sdk_tpu.services.network.ledger import FinalityEvent, Network, TxStatus
from fabric_token_sdk_tpu.services.network.orderer import BlockPolicy, Orderer
from fabric_token_sdk_tpu.services.network.remote import (
    LedgerServer,
    RemoteError,
    RemoteNetwork,
)
from fabric_token_sdk_tpu.services.ttx import Party, Transaction
from fabric_token_sdk_tpu.utils import faults
from fabric_token_sdk_tpu.utils import metrics as mx
from fabric_token_sdk_tpu.utils import sysmon

REPO = os.path.join(os.path.dirname(__file__), "..")


def _ftstop():
    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftstop
    finally:
        sys.path.pop(0)
    return ftstop


# ------------------------------------------------------------ quantiles


def test_histogram_quantiles_interpolate_within_buckets():
    h = mx.Histogram("q.test", buckets=(1.0, 2.0, 4.0, 8.0))
    for v in [0.5] * 50 + [3.0] * 40 + [7.0] * 10:
        h.observe(v)
    # rank 50 falls in the first bucket: interpolated within [min, 1.0]
    assert 0.5 <= h.quantile(0.5) <= 1.0
    # rank 95 falls in the (4, 8] bucket: interpolated, clamped to max
    assert 4.0 < h.quantile(0.95) <= 7.0
    assert h.quantile(0.99) <= 7.0  # never above the observed max
    snap = h.snapshot()
    assert snap["p50"] == pytest.approx(h.quantile(0.5))
    assert snap["p95"] == pytest.approx(h.quantile(0.95))
    assert snap["p99"] == pytest.approx(h.quantile(0.99))


def test_histogram_quantile_single_value_is_exact():
    h = mx.Histogram("q.single", buckets=(1.0, 4.0))
    h.observe(3.0)
    # clamping to [min, max] makes a single observation report itself
    assert h.quantile(0.5) == 3.0
    assert h.quantile(0.99) == 3.0


def test_histogram_quantile_empty_and_inf_bucket():
    h = mx.Histogram("q.empty", buckets=(1.0,))
    assert h.quantile(0.5) is None
    assert "p50" not in h.snapshot()
    # everything beyond the last bound: the +Inf bucket reports max
    h.observe(5.0)
    h.observe(50.0)
    assert h.quantile(0.95) == 50.0


def test_prometheus_export_carries_quantile_series():
    reg = mx.Registry()
    h = reg.histogram("ops.check.seconds")
    h.observe(0.2)
    h.observe(0.4)
    text = reg.to_prometheus()
    assert "fts_ops_check_seconds_p50" in text
    assert "fts_ops_check_seconds_p95" in text
    assert "fts_ops_check_seconds_p99" in text


# ------------------------------------------------------------ memory telemetry


def test_sysmon_host_rss_and_gauges():
    assert sysmon.host_rss_bytes() > 1024 * 1024  # a live interpreter
    s = sysmon.sample()
    assert s["rss_bytes"] > 0
    assert mx.gauge("proc.rss.bytes").value > 0
    assert mx.gauge("proc.rss.peak.bytes").value >= mx.gauge("proc.rss.bytes").value * 0


def test_sysmon_device_memory_and_stage_high_water():
    # device_put only — no XLA program is compiled by sampling
    import numpy as np
    import jax.numpy as jnp

    compiled_before = mx.REGISTRY.histogram(
        "jax.core.compile.backend_compile_duration.seconds"
    ).count
    a = jnp.asarray(np.zeros((256, 256), dtype=np.int32))
    dev = sysmon.device_memory_bytes()
    assert dev is not None and dev >= a.nbytes
    sysmon._last_stage_sample = 0.0  # reset the throttle for the test
    s = sysmon.sample_stages()
    assert s is not None
    assert mx.gauge("stages.mem.high_water.bytes").value >= a.nbytes
    assert mx.gauge("stages.mem.rss_high_water.bytes").value > 0
    # throttled second call inside FTS_MEM_SAMPLE_S
    assert sysmon.sample_stages() is None
    compiled_after = mx.REGISTRY.histogram(
        "jax.core.compile.backend_compile_duration.seconds"
    ).count
    assert compiled_after == compiled_before, (
        "memory sampling must not compile XLA programs"
    )
    del a


# ------------------------------------------------------------ orderer gauges


def test_queue_depth_and_inflight_gauges_track_lifecycle():
    seen = {}

    def commit(batch):
        # mid-commit: the queue was drained by the cut, but every cut tx
        # is still IN FLIGHT until resolved
        seen["depth_mid"] = mx.gauge("orderer.queue.depth").value
        seen["inflight_mid"] = ordr.inflight()
        for s in batch:
            s._resolve(FinalityEvent(s.request.anchor, TxStatus.VALID))

    ordr = Orderer(commit, BlockPolicy(max_block_txs=8))
    subs = [ordr.enqueue(TokenRequest(anchor=f"t{i}")) for i in range(3)]
    assert mx.gauge("orderer.queue.depth").value == 3
    assert ordr.inflight() == 3
    ordr.flush()
    assert seen["depth_mid"] == 0  # cut drained the queue
    assert seen["inflight_mid"] == 3  # but nothing was resolved yet
    assert ordr.pending() == 0
    assert ordr.inflight() == 0
    assert mx.gauge("ledger.inflight").value == 0
    # submit→finality latency was observed for every tx, and is nonzero
    h = mx.REGISTRY.histogram("network.submit_to_finality.seconds")
    assert h.count >= 3
    assert all(s.done() for s in subs)
    # double resolve is idempotent (no negative inflight)
    subs[0]._resolve(FinalityEvent("t0", TxStatus.INVALID))
    assert ordr.inflight() == 0


# ------------------------------------------------------------ live node fixture


def _node(tmp_path=None, **client_kw):
    pp = FabTokenPublicParams()
    wal = str(tmp_path / "ledger.wal") if tmp_path is not None else None
    net = Network(
        RequestValidator(FabTokenDriver(pp)),
        policy=BlockPolicy(max_block_txs=4, min_batch=1),
        wal_path=wal,
    )
    server = LedgerServer(network=net).start()
    client = RemoteNetwork(server.address, **client_kw)
    issuer_p = Party("issuer", FabTokenDriver(pp), client)
    alice_p = Party("alice", FabTokenDriver(pp), client)
    iw = issuer_p.new_issuer_wallet("issuer")
    pp.add_issuer(iw.identity)
    alice = alice_p.new_owner_wallet("alice", anonymous=False)
    return server, client, issuer_p, alice

def _issue_requests(issuer_p, alice, n, tag="ops"):
    reqs = []
    for i in range(n):
        tx = Transaction(issuer_p, f"{tag}-{i}")
        tx.issue("issuer", "USD", [1 + i], [alice.recipient_identity()],
                 anonymous=False)
        tx.collect_endorsements(None)
        reqs.append(tx.request.to_bytes())
    return reqs


# ------------------------------------------------------------ acceptance


def test_ops_rpcs_answer_live_during_slow_commits(tmp_path):
    """ISSUE acceptance: poll `ops.health`/`ops.metrics` MID-RUN while
    commits are artificially slow — queue depth, height and a nonzero
    block-commit p95 come back live, and no probe ever waits behind a
    commit."""
    server, client, issuer_p, alice = _node(tmp_path)
    probe = RemoteNetwork(server.address)  # separate "monitoring" client
    delay_s = 0.3
    n_txs = 8
    try:
        reqs = _issue_requests(issuer_p, alice, n_txs)
        # every block commit now sleeps inside the commit path
        faults.arm("ledger.commit_block", "delay", delay_s=delay_s)
        errors = []

        def submitter(chunk):
            try:
                for rb in chunk:
                    ev = client.submit(rb)
                    assert ev.status == TxStatus.VALID, ev.message
            except Exception as e:  # pragma: no cover
                errors.append(e)

        commit_h = mx.REGISTRY.histogram("ledger.block.commit.seconds")
        pre_sum, pre_count = commit_h.sum, commit_h.count
        threads = [
            threading.Thread(target=submitter, args=(reqs[i::2],))
            for i in range(2)
        ]
        for t in threads:
            t.start()

        probes, peak_inflight, mid_hist = [], 0, None
        while any(t.is_alive() for t in threads):
            t0 = time.monotonic()
            h = probe.ops_health()
            probes.append(time.monotonic() - t0)
            peak_inflight = max(peak_inflight, h["inflight"])
            if mid_hist is None and h["height"] >= 2:
                # mid-run metrics snapshot: quantiles served live
                mid_hist = probe.ops_metrics()["histograms"].get(
                    "ledger.block.commit.seconds", {}
                )
            time.sleep(0.02)
        for t in threads:
            t.join()
        assert not errors, errors
    finally:
        faults.clear()
        server.stop()

    assert len(probes) >= 5, "workload finished before probes could sample"
    # no probe ever blocked behind a sleeping commit
    assert max(probes) < delay_s, (
        f"health probe blocked behind a commit: max={max(probes):.3f}s"
    )
    # the workload was genuinely in flight while we probed
    assert peak_inflight >= 1
    # the mid-run snapshot served live quantiles AND saw the injected
    # commit latency. The process registry is shared across the whole
    # pytest session, so absolute p95/max depend on what earlier tests
    # contributed (hundreds of fast commits from the batch-sign soak
    # smoke, multi-second zk commits from test_orderer) — assert on the
    # DELTA this test's own workload added instead: at least one block
    # committed during the run, and the added wall time carries the
    # injected delay. Quantile interpolation itself is pinned by the
    # dedicated Histogram quantile tests above.
    assert mid_hist is not None and mid_hist.get("p95") is not None
    assert mid_hist.get("count", 0) > pre_count
    assert mid_hist.get("sum", 0.0) - pre_sum >= delay_s * 0.9
    # final health is consistent (server stopped — read the ledger
    # directly): all txs finalized, nothing queued or in flight
    assert server.network.health()["txs_final"] == n_txs
    assert server.network.health()["queue_depth"] == 0
    assert server.network.health()["inflight"] == 0
    wal = server.network.health()["wal"]
    assert wal is not None and wal["bytes"] > 0 and not wal["poisoned"]
    lb = server.network.health()["last_block"]
    assert lb is not None and lb["commit_s"] >= delay_s * 0.9
    # `overlap_s` rides along only when the pipelined engine is active
    assert set(lb["breakdown"]) - {"overlap_s"} == {
        "queue_wait_max_s", "grouping_s", "device_verify_s",
        "sign_verify_s", "host_validate_s", "host_unmarshal_s",
        "host_fiat_shamir_s", "host_sig_verify_s",
        "host_conservation_s", "host_input_match_s", "wal_s", "merge_s",
        "host_sign_batch_s", "host_proof_batch_s",
        "host_conservation_batch_s",
    }


def test_ops_flight_tail_and_metrics_snapshot_over_wire(tmp_path):
    server, client, issuer_p, alice = _node(tmp_path)
    try:
        for rb in _issue_requests(issuer_p, alice, 2, tag="fl"):
            assert client.submit(rb).status == TxStatus.VALID
        events = client.ops_flight(16)
        kinds = {e["kind"] for e in events}
        assert "block.commit" in kinds and "finality" in kinds
        snap = client.ops_metrics()
        assert snap["counters"]["ledger.blocks.committed"] >= 2
        h = snap["histograms"]["network.submit_to_finality.seconds"]
        assert h["count"] >= 2 and h["p95"] > 0
        health = client.ops_health()
        assert health["uptime_s"] >= 0 and health["height"] == client.height()
        # a health probe refreshes the memory gauges server-side
        assert snap["gauges"].get("proc.rss.bytes", 0) > 0
    finally:
        server.stop()


def test_ops_calls_ride_idempotent_retry_path():
    """Satellite: ops RPCs go through `_call_idempotent` — a dropped
    connection is retried with backoff, not surfaced to the monitor."""
    server, client, issuer_p, alice = _node(retries=2, backoff_s=0.001)
    try:
        before = mx.REGISTRY.counter("remote.retry.ops.health").value
        faults.arm("remote.send", "drop", count=1)
        h = client.ops_health()
        assert h["height"] == 0
        assert mx.REGISTRY.counter("remote.retry.ops.health").value == before + 1
    finally:
        faults.clear()
        server.stop()


def test_stopping_server_answers_probes_typed():
    """Satellite: a stopping node answers in-flight ops probes with a
    typed `NodeStopped` error instead of a silently dropped connection."""
    server, client, issuer_p, alice = _node(retries=0)
    try:
        assert client.ops_health()["height"] == 0
        server._stopping.set()  # the stop() entry point, before severing
        with pytest.raises(RemoteError) as ei:
            client.ops_health()
        assert ei.value.error_class == "NodeStopped"
        assert mx.REGISTRY.counter("remote.dispatch.stopped").value >= 1
    finally:
        server.stop()


# ------------------------------------------------------------ compile budget


@pytest.mark.skipif(
    os.environ.get("FTS_WARMUP") != "1",
    reason="needs the FTS_WARMUP=1 session precompile (conftest fixture)",
)
def test_ops_plane_zero_cache_misses_after_warmup():
    """ISSUE acceptance: a warmup-then-ops-plane run — a batched zk
    block committed WHILE ops RPCs poll the node — misses the
    compilation cache zero times and compiles zero new programs. The ops
    plane (quantiles, memory sampling in `run_rows`, health/metrics/
    flight serving) must add NO XLA programs."""
    import random

    from test_orderer import build_env, issue_to, manual_transfer
    from fabric_token_sdk_tpu.crypto.setup import setup
    from fabric_token_sdk_tpu.drivers.zkatdlog import ZKATDLogDriver

    pp = setup(base=4, exponent=2, rng=random.Random(0xF75))
    network, parties, issuer, alice, bob = build_env(
        lambda: ZKATDLogDriver(pp), BlockPolicy(max_block_txs=8, min_batch=2)
    )
    alice_p = parties["alice-node"]
    issue_to(parties, alice, [5] * 4, "ops-seed")
    reqs = [
        manual_transfer(alice_p, tid, 5, bob.recipient_identity(), f"ops-{i}")
        for i, tid in enumerate(alice_p.vault.token_ids())
    ]
    server = LedgerServer(network=network).start()
    # the submit blocks for the whole block commit — minutes on a small
    # CPU host where the emulated device verify is slow. The PROBE keeps
    # the default 30s timeout: every poll must answer fast regardless.
    client = RemoteNetwork(server.address, timeout=900.0)
    probe = RemoteNetwork(server.address)
    misses_before = mx.REGISTRY.counter(
        "jax.compilation_cache.cache_misses"
    ).value
    stop = threading.Event()
    polled = []

    def poller():
        while not stop.is_set():
            polled.append(probe.ops_health()["height"])
            probe.ops_metrics()
            time.sleep(0.05)

    t = threading.Thread(target=poller)
    t.start()
    try:
        events = client.submit_many([r.to_bytes() for r in reqs])
        assert all(e.status.value == "Valid" for e in events)
    finally:
        stop.set()
        t.join()
        server.stop()
    assert polled, "ops plane never polled during the run"
    # `cache_misses == 0` IS the no-new-XLA-programs signal: this jax
    # fires backend_compile events on persistent-cache LOADS too, so the
    # histogram count moves on a warm first materialization — only a
    # MISS means a program outside the canonical warmed set appeared
    misses = (
        mx.REGISTRY.counter("jax.compilation_cache.cache_misses").value
        - misses_before
    )
    assert misses == 0, f"ops-plane run missed the cache {misses} time(s)"
    # the quantiles the run produced are in the registry snapshot
    snap = mx.REGISTRY.snapshot()
    assert snap["histograms"]["ledger.block.commit.seconds"]["p95"] > 0


# ------------------------------------------------------------ ftstop


def _full_record(**over):
    import bench

    r = bench.headline_result(
        rate=100.0, platform="cpu", batch=8, runs=1, warm_s=1.0,
        provegen_s=2.0, provegen_host_s=0.5, prove_txs=4, prove_rate=2.0,
        host_rate=1.0, prove_degraded=False, setup_s=0.1, stage_warmup_s=5.0,
    )
    r.update({"block_txs_per_s": 50.0, "block_vs_baseline": 0.376,
              "block_txs": 8, "block_batched_frac": 1.0,
              "block_provegen_s": 1.0, "wal_overhead_frac": 0.01})
    r.update(over)
    return r


def test_ftstop_compare_flags_injected_regression(tmp_path, capsys):
    """ISSUE acceptance: an injected regression between two synthetic
    bench records is flagged (and gates via the exit code)."""
    ftstop = _ftstop()
    old = _full_record()
    new = _full_record(value=55.0, block_txs_per_s=55.0)  # −45% verify
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    rc = ftstop.main(["compare", str(a), str(b), "--threshold", "0.1"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out and "value" in out
    vmap = {
        v["metric"]: v["verdict"]
        for v in ftstop.compare_records(old, new, 0.1)
    }
    assert vmap["value"] == "regression"
    assert vmap["wal_overhead_frac"] == "ok"
    # improvements and cost-metric direction
    vmap = {
        v["metric"]: v["verdict"]
        for v in ftstop.compare_records(
            old, _full_record(value=150.0, stage_warmup_s=50.0), 0.1
        )
    }
    assert vmap["value"] == "improvement"
    assert vmap["stage_warmup_s"] == "regression"  # cost metric grew 10x
    # within threshold: rc 0
    c = tmp_path / "same.json"
    c.write_text(json.dumps(_full_record(value=99.0)))
    assert ftstop.main(["compare", str(a), str(c)]) == 0


def test_ftstop_compare_history_median_baseline(tmp_path, capsys):
    import bench

    ftstop = _ftstop()
    hist = tmp_path / "BENCH_history.jsonl"
    # two deadline-degraded rounds (value=0) must NOT poison the baseline
    for _ in range(2):
        bench.append_history(
            bench.degraded_result("cpu", 2000.0, {}), path=str(hist)
        )
    for v in (100.0, 110.0, 90.0):
        bench.append_history(_full_record(value=v), path=str(hist))
    bench.append_history(_full_record(value=40.0), path=str(hist))
    hist.write_text(hist.read_text() + "{torn\n")  # torn tail tolerated
    rc = ftstop.main(["compare", "--history", str(hist)])
    out = capsys.readouterr().out
    assert rc == 1  # 40 vs median(100, 110, 90) = 100 → regression
    assert "median(3 prior full rounds)" in out  # degraded rounds excluded
    assert "REGRESSION" in out
    # --no-fail reports but does not gate
    assert ftstop.main(["compare", "--history", str(hist), "--no-fail"]) == 0
    capsys.readouterr()
    # an all-degraded baseline window is an error, not a silent diff
    short = tmp_path / "short.jsonl"
    bench.append_history(bench.degraded_result("cpu", 8.0, {}), path=str(short))
    bench.append_history(_full_record(), path=str(short))
    assert ftstop.main(["compare", "--history", str(short)]) == 2


def test_ftstop_compare_rejects_schema_invalid_records(tmp_path, capsys):
    ftstop = _ftstop()
    a = tmp_path / "bad.json"
    a.write_text(json.dumps({"metric": "wrong_name", "value": "NaN"}))
    b = tmp_path / "good.json"
    b.write_text(json.dumps(_full_record()))
    assert ftstop.main(["compare", str(a), str(b)]) == 2


def test_ftsmetrics_show_prints_ops_summary(tmp_path, capsys):
    """Satellite: the one-line ops summary (queue depth, memory
    high-water, block-commit + submit→finality p50/p95/p99) renders from
    any snapshot sidecar."""
    sys.path.insert(0, os.path.join(REPO, "cmd"))
    try:
        import ftsmetrics
    finally:
        sys.path.pop(0)
    reg = mx.Registry()
    reg.gauge("orderer.queue.depth").set(3)
    reg.gauge("ledger.inflight").set(5)
    reg.gauge("proc.rss.peak.bytes").set(123e6)
    reg.gauge("stages.mem.high_water.bytes").set(45e6)
    h = reg.histogram("ledger.block.commit.seconds")
    h.observe(0.3)
    h.observe(0.5)
    reg.histogram("network.submit_to_finality.seconds").observe(0.31)
    path = tmp_path / "ops.metrics.json"
    path.write_text(reg.to_json())
    ftsmetrics.show(str(path))
    out = capsys.readouterr().out
    assert "ops summary:" in out
    assert "queue_depth=3" in out and "inflight=5" in out
    assert "rss_peak=123.0MB" in out and "dev_mem_hw=45.0MB" in out
    assert "block_commit[p50/p95/p99]=" in out
    assert "finality[p50/p95/p99]=310.0ms/310.0ms/310.0ms" in out


def test_ftstop_top_renders_live_rows(tmp_path, capsys):
    ftstop = _ftstop()
    server, client, issuer_p, alice = _node(tmp_path)
    try:
        for rb in _issue_requests(issuer_p, alice, 2, tag="top"):
            assert client.submit(rb).status == TxStatus.VALID
        host, port = server.address
        rc = ftstop.top(f"{host}:{port}", interval=0.05, count=2)
    finally:
        server.stop()
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert rc == 0 and len(lines) == 2
    assert "height=2" in lines[0]
    assert "p95.commit=" in lines[0]
    assert "tx/s=" in lines[1] and "wal=" in lines[0]
    # format_row is pure: a synthetic health/snapshot renders too
    row = ftstop.format_row({"uptime_s": 1.0, "height": 3}, {}, None, None)
    assert "height=3" in row
